#!/usr/bin/env python
"""Static host-sync check for the training hot path (DESIGN-PERF.md).

Thin wrapper: the check itself lives in
``scripts/analysis/host_sync.py`` on the shared pass framework
(DESIGN-ANALYSIS.md); this CLI and its ``check()`` API are kept for
the historic call sites.  Exit 0 clean; exit 1 with a report.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import core, host_sync  # noqa: E402
from analysis.host_sync import ALLOWED_SYNC, HOT_MODULES  # noqa: F401,E402


def check() -> List[Tuple[str, int, str]]:
    """Violations as (path-relative-to-paddle_tpu, line, message)."""
    cb = core.Codebase.load()
    prefix = core.PKG_REL + os.sep
    return [(v.rel[len(prefix):] if v.rel.startswith(prefix) else v.rel,
             v.line, v.message)
            for v in core.run_pass(cb, host_sync)]


def main() -> int:
    violations = check()
    if not violations:
        print(host_sync.OK_MESSAGE)
        return 0
    print(host_sync.REPORT_HEADER)
    for rel, line, msg in violations:
        print(f"  paddle_tpu/{rel}:{line}: {msg}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
