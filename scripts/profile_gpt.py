"""Decompose the GPT-2-small bench step time on one chip.

The axon-tunnel backend only reports true wall time for a
data-dependency chain ended by a host transfer (block_until_ready on a
remote buffer can return early), so every measurement here is N chained
train steps followed by float(loss) — the bench.py methodology.

Decomposition by config deltas:
  - layers 12 vs 6          -> per-decoder-layer cost
  - flash on vs off         -> attention kernel contribution
  - AdamW vs SGD            -> optimizer update cost
  - full vs tiny vocab head -> lm-head + loss contribution
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def step_time(cfg_kw, opt_name="adamw", steps=12, batch=8, seq=1024):
    import paddle_tpu as paddle
    from paddle_tpu import amp, optimizer
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.runner import DistributedRunner
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    paddle.seed(0)
    base = dict(vocab_size=50304, hidden_size=768, num_hidden_layers=12,
                num_attention_heads=12, intermediate_size=3072,
                max_position_embeddings=1024, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0, use_flash_attention=True)
    base.update(cfg_kw)
    cfg = GPTConfig(**base)
    net = GPTForCausalLM(cfg)
    if opt_name == "adamw":
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=net.parameters(),
                              multi_precision=True)
    else:
        opt = optimizer.SGD(learning_rate=1e-4,
                            parameters=net.parameters())
    amp.decorate(net, opt, level="O2", dtype="bfloat16")
    rng = np.random.RandomState(0)
    x = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    y = np.roll(x, -1, axis=1)
    mesh = collective.build_mesh({})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt, GPTPretrainingCriterion(),
                               mesh=mesh)
    xs = [Tensor(jax.device_put(x))]
    ys = [Tensor(jax.device_put(y))]
    float(runner.train_step(xs, ys))   # compile
    float(runner.train_step(xs, ys))   # warmup (pipe prime)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = runner.train_step(xs, ys)
    float(loss)
    return (time.perf_counter() - t0) / steps * 1000.0


def main():
    import subprocess, sys, os, json
    # run each config in a separate process (one backend init each, and
    # isolates any compile-cache contention)
    if len(sys.argv) > 1:
        spec = json.loads(sys.argv[1])
        print("MS", step_time(spec["cfg"], spec.get("opt", "adamw")),
              flush=True)
        return
    cases = [
        ("baseline L12 flash adamw", {"cfg": {}}),
        ("L6", {"cfg": {"num_hidden_layers": 6}}),
        ("L12 no-flash(sdpa)", {"cfg": {"use_flash_attention": False}}),
        ("L12 sgd", {"cfg": {}, "opt": "sgd"}),
        ("L12 vocab 4k", {"cfg": {"vocab_size": 4096}}),
    ]
    results = {}
    for name, spec in cases:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), json.dumps(spec)],
            capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        ms = None
        for ln in p.stdout.splitlines():
            if ln.startswith("MS "):
                ms = float(ln.split()[1])
        results[name] = ms
        print(f"{name:28s} {ms if ms else -1:8.2f} ms/step", flush=True)
        if ms is None:
            print(p.stdout[-1500:], p.stderr[-1500:])
    if results.get("baseline L12 flash adamw") and results.get("L6"):
        per_layer = (results["baseline L12 flash adamw"]
                     - results["L6"]) / 6.0
        print(f"per-decoder-layer (fwd+bwd): {per_layer:.2f} ms")


if __name__ == "__main__":
    main()
