"""knob-consumption pass: every DistributedStrategy knob is consumed
or explicitly refused, never silently dropped (the PR-11 strategy
contract; DESIGN-ANALYSIS.md §knob-consumption).

``DistributedStrategy.to_dict()`` exports exactly the ``self.X``
attributes ``__init__`` assigns; a knob a user sets that nothing
reads is the worst failure mode a config object has — training runs,
silently, without the feature.  Rules:

1. every exported knob is either *consumed* (an attribute read
   ``<obj>.<knob>`` / literal ``getattr(s, "<knob>")`` / literal
   ``d["<knob>"]`` somewhere in the package outside the strategy
   module) or *refused* (listed in ``fleet.py``'s
   ``_REFUSED_STRATEGY_KNOBS`` ledger, whose runtime gate raises when
   a refused knob is changed from its default);
2. the refusal ledger stays consistent: every refused name is a real
   knob, carries a reason, and is not also consumed;
3. computed knob names are rejected — ``getattr(strategy, var)`` on a
   strategy receiver defeats the census this pass performs.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set

from . import core
from .core import Codebase, Violation

NAME = "knob-consumption"
OK_MESSAGE = ("strategy-knob coverage OK: every DistributedStrategy "
              "knob is consumed or refused on record")
REPORT_HEADER = "knob-consumption violations:"

STRATEGY_MOD = os.path.join(core.PKG_REL, "distributed", "fleet",
                            "base", "distributed_strategy.py")
FLEET_MOD = os.path.join(core.PKG_REL, "distributed", "fleet",
                         "fleet.py")

# receiver names that read as "a strategy object" for the
# computed-name rule
_STRATEGY_RECEIVERS = {"s", "strategy", "_strategy", "strat"}


def exported_knobs(cb: Codebase) -> Dict[str, int]:
    """knob name -> defining line, from ``self.X = ...`` assignments
    in DistributedStrategy.__init__ (== the to_dict key set)."""
    mod = cb.get(STRATEGY_MOD)
    if mod is None:
        return {}
    out: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name == "DistributedStrategy"):
            continue
        for item in node.body:
            if not (isinstance(item, ast.FunctionDef)
                    and item.name == "__init__"):
                continue
            for stmt in ast.walk(item):
                if not isinstance(stmt, ast.Assign):
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        out.setdefault(t.attr, stmt.lineno)
    return out


def refusal_ledger(cb: Codebase) -> Dict[str, int]:
    """Keys of the ``_REFUSED_STRATEGY_KNOBS`` dict literal in
    fleet.py -> line (values are the reasons, checked non-empty)."""
    mod = cb.get(FLEET_MOD)
    if mod is None:
        return {}
    out: Dict[str, int] = {}
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_REFUSED_STRATEGY_KNOBS"
                and isinstance(node.value, ast.Dict)):
            continue
        for k in node.value.keys:
            name = core.const_str(k)
            if name is not None:
                out[name] = k.lineno
    return out


def run(cb: Codebase) -> List[Violation]:
    violations: List[Violation] = []
    knobs = exported_knobs(cb)
    if not knobs:
        violations.append(Violation(
            STRATEGY_MOD, 0,
            "could not locate DistributedStrategy.__init__ self.X "
            "assignments — the knob census has nothing to check"))
        return violations
    refused = refusal_ledger(cb)
    consumed: Set[str] = set()
    for mod in cb.iter_modules():
        if mod.rel == STRATEGY_MOD:
            continue
        for node in ast.walk(mod.tree):
            # <obj>.<knob> attribute read
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.attr in knobs:
                consumed.add(node.attr)
            elif isinstance(node, ast.Call):
                fname = core.call_name(node)
                # getattr(s, "knob"[, default]) — literal consumption;
                # computed name on a strategy receiver — violation
                if fname == "getattr" and len(node.args) >= 2:
                    key = core.const_str(node.args[1])
                    if key is not None:
                        if key in knobs:
                            consumed.add(key)
                    elif isinstance(node.args[0], ast.Name) and \
                            node.args[0].id in _STRATEGY_RECEIVERS:
                        violations.append(Violation(
                            mod.rel, node.lineno,
                            "computed strategy-knob name "
                            "(getattr with a non-literal key on a "
                            "strategy receiver) — knob reads must be "
                            "statically auditable"))
                # d.get("knob") / d["knob"] on exported config dicts
                elif fname == "get" and node.args:
                    key = core.const_str(node.args[0])
                    if key in knobs:
                        consumed.add(key)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load):
                key = core.const_str(node.slice)
                if key in knobs:
                    consumed.add(key)
    # rule 2: ledger consistency
    for name, line in sorted(refused.items()):
        if name not in knobs:
            violations.append(Violation(
                FLEET_MOD, line,
                f"refusal ledger names {name!r}, which is not a "
                "DistributedStrategy knob — stale entry or typo"))
        elif name in consumed:
            violations.append(Violation(
                FLEET_MOD, line,
                f"{name!r} is in the refusal ledger but also "
                "consumed — drop the refusal (the knob works) or "
                "the consumer (it doesn't)"))
    # rule 1: every knob consumed or refused
    for name, line in sorted(knobs.items()):
        if name not in consumed and name not in refused:
            violations.append(Violation(
                STRATEGY_MOD, line,
                f"strategy knob {name!r} is neither consumed nor "
                "refused — a user setting it trains silently without "
                "the feature (wire it, or add it to fleet.py's "
                "_REFUSED_STRATEGY_KNOBS with the reason)"))
    return violations
