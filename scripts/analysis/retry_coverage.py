"""retry-coverage pass: network / checkpoint IO routes through the
resilience retry layer (DESIGN-RESILIENCE.md; ported
verdict-unchanged from scripts/check_retry_coverage.py).

A bare ``urlopen`` or orbax save/restore call is a latent pod-killer
on real infrastructure, where transient 5xx / NFS stalls are routine:

1. ``urllib.request.urlopen`` (or bare ``urlopen``) may only be called
   inside a function that routes through ``retry_call(...)`` /
   ``@retryable`` — or in an allowlisted module that documents why it
   is exempt.
2. Orbax manager IO (``self._mgr.save/restore``) in the checkpoint
   manager must likewise sit in retry-routed functions.
"""

from __future__ import annotations

import ast
import os
from typing import List

from . import core
from .core import Codebase, Violation

NAME = "retry-coverage"
OK_MESSAGE = ("retry coverage OK: all urlopen/checkpoint-IO sites "
              "route through resilience.retry")
REPORT_HEADER = "retry coverage violations:"

# modules where a bare urlopen is acceptable, with the reason on record
URLOPEN_ALLOWLIST = {
    # the retry layer itself obviously sits below retry_call
    os.path.join(core.PKG_REL, "distributed", "resilience", "retry.py"),
    # the controller's fleet metrics scrape is best-effort BY DESIGN:
    # a failed member scrape means "absent this round" (counted on
    # fleet_scrape_errors_total), never a judgment, and the next
    # scrape interval retries naturally — blocking the 4 Hz watch
    # loop on urlopen retries would delay the failure detection the
    # loop exists for (DESIGN-OBSERVABILITY.md §Distributed plane)
    os.path.join(core.PKG_REL, "distributed", "launch", "controller.py"),
}

CHECKPOINT_MANAGER = os.path.join(core.PKG_REL, "distributed",
                                  "checkpoint", "manager.py")


def _is_urlopen(call: ast.Call) -> bool:
    return core.call_name(call) == "urlopen"


def _is_ckpt_io(call: ast.Call) -> bool:
    """self._mgr.save(...) / self._mgr.restore(...) — the raw orbax
    manager IO inside the checkpoint manager."""
    f = call.func
    return (isinstance(f, ast.Attribute)
            and f.attr in ("save", "restore")
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "_mgr")


def _routes_through_retry(func: ast.AST) -> bool:
    """The function either calls retry_call / retry.retry_call or is
    wrapped by @retryable."""
    for deco in getattr(func, "decorator_list", []):
        base = deco.func if isinstance(deco, ast.Call) else deco
        name = base.attr if isinstance(base, ast.Attribute) else \
            getattr(base, "id", "")
        if name == "retryable":
            return True
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and \
                core.call_name(node) == "retry_call":
            return True
    return False


def _retry_wrapped_names(tree: ast.Module) -> set:
    """Names of functions handed to ``retry_call`` as the callable —
    ``retry_call(self._send, ...)`` / ``retry_call(_write, ...)``:
    their bodies hold the raw IO by design."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if core.call_name(node) != "retry_call":
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute):
            names.add(arg.attr)
        elif isinstance(arg, ast.Name):
            names.add(arg.id)
    return names


def run(cb: Codebase) -> List[Violation]:
    violations: List[Violation] = []
    for rel, (lineno, msg) in sorted(cb.broken.items()):
        if rel.startswith(core.PKG_REL):
            violations.append(Violation(rel, lineno,
                                        f"syntax error: {msg}"))
    for mod in cb.iter_modules():
        _, chains = core.enclosing_chains(mod.tree)
        wrapped = _retry_wrapped_names(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = None
            if _is_urlopen(node) and mod.rel not in URLOPEN_ALLOWLIST:
                kind = "urlopen"
            elif mod.rel == CHECKPOINT_MANAGER and _is_ckpt_io(node):
                kind = "checkpoint-IO"
            if kind is None:
                continue
            chain = chains.get(id(node), [])
            if not chain:
                violations.append(Violation(
                    mod.rel, node.lineno,
                    f"module-level {kind} call (unretried)"))
            elif not any(_routes_through_retry(fn)
                         or fn.name in wrapped for fn in chain):
                violations.append(Violation(
                    mod.rel, node.lineno,
                    f"{kind} call in {chain[-1].name}() does not "
                    "route through resilience.retry "
                    "(retry_call/@retryable)"))
    return violations
