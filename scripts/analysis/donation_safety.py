"""donation-safety pass: donated buffers are never reused, and
donation in the shard_map hazard modules stays behind the
``donate_carry=`` knob (DESIGN-ANALYSIS.md §donation-safety).

``donate_argnums`` hands the *buffer* to XLA: after the dispatch the
Python name still points at a deleted array, and the next touch
raises (best case) or reads garbage through an alias (worst case —
this container's jaxlib corrupts buffers donated through shard_map
manual collectives, the DESIGN-DCN.md caveat).  Two rules:

1. **Use-after-donation.**  Where a module binds a name to a
   jit-with-donation (``X = jax.jit(f, donate_argnums=(...))`` /
   ``guarded_jit(...)``), every call ``X(a, b, ...)`` donates the
   arguments at those positions; a plain-name argument at a donated
   position that is *read again* before being rebound in the same
   function is a use-after-donation.
2. **Knob-routed donation in hazard modules.**  Modules that use
   ``shard_map`` may not hard-code ``donate_argnums`` literals: the
   donation decision must flow through a ``donate_carry`` parameter
   (or a name computed from one), so the shard_map donation caveat
   has one opt-in switch instead of scattered literals.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Tuple

from . import core
from .core import Codebase, Violation

NAME = "donation-safety"
OK_MESSAGE = ("donation-safety OK: no donated-arg reuse; hazard-"
              "module donation routes through donate_carry=")
REPORT_HEADER = "donation-safety violations:"

_JIT_NAMES = {"jit", "guarded_jit"}


def _donation_kwarg(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return kw
    return None


def _literal_positions(node: ast.AST):
    """donate_argnums literal -> tuple of ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    return None


def _bind_target(stmt: ast.stmt):
    """``X = jit(...)`` / ``self._x = jit(...)`` -> ('X',) key."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return None
    t = stmt.targets[0]
    if isinstance(t, ast.Name):
        return ("name", t.id)
    if isinstance(t, ast.Attribute) and \
            isinstance(t.value, ast.Name) and t.value.id == "self":
        return ("self", t.attr)
    return None


def _donating_bindings(tree: ast.Module) -> Dict[Tuple[str, str],
                                                 Tuple[int, ...]]:
    """Names/self-attrs bound to a jit with a literal donate_argnums
    in this module."""
    out: Dict[Tuple[str, str], Tuple[int, ...]] = {}
    for stmt in ast.walk(tree):
        key = _bind_target(stmt) if isinstance(stmt, ast.Assign) \
            else None
        if key is None or not isinstance(stmt.value, ast.Call):
            continue
        call = stmt.value
        if core.call_name(call) not in _JIT_NAMES:
            continue
        kw = _donation_kwarg(call)
        if kw is None:
            continue
        pos = _literal_positions(kw.value)
        if pos:
            out[key] = pos
    return out


def _call_key(call: ast.Call):
    f = call.func
    if isinstance(f, ast.Name):
        return ("name", f.id)
    if isinstance(f, ast.Attribute) and \
            isinstance(f.value, ast.Name) and f.value.id == "self":
        return ("self", f.attr)
    return None


def _rebinds(stmt: ast.stmt, name: str) -> bool:
    """Does this statement bind ``name`` (assignment target, for-loop
    target, with-as, aug-assign)?"""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.With):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name) and n.id == name:
                return True
    return False


def _reads(stmt: ast.stmt, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               and isinstance(n.ctx, ast.Load)
               for n in ast.walk(stmt))


def _check_use_after(fn, call: ast.Call, donated: List[str],
                     rel: str, out: List[Violation]) -> None:
    """Scan the statements of ``fn`` after the one containing ``call``
    for a read-before-rebind of each donated name."""
    # statement list in source order: enough for the linear
    # post-call scan (nested scopes that rebind break the scan)
    stmts = [n for n in ast.walk(fn) if isinstance(n, ast.stmt)]
    stmts.sort(key=lambda s: (s.lineno, s.col_offset))
    containing = None
    for s in stmts:
        if any(n is call for n in ast.walk(s)):
            containing = s       # innermost statement wins (last hit)
    if containing is None:
        return
    for name in donated:
        # the containing statement itself may rebind (the canonical
        # ``state = step(state, ...)`` carry idiom)
        if _rebinds(containing, name):
            continue
        for s in stmts:
            if s.lineno <= containing.lineno or s is containing:
                continue
            if _rebinds(s, name) and not _reads(s, name):
                break
            if _reads(s, name):
                out.append(Violation(
                    rel, s.lineno,
                    f"{name!r} was donated to the compiled entry at "
                    f"line {call.lineno} and is read again here "
                    "before rebinding — the donated buffer is dead "
                    "after dispatch (rebind from the entry's return "
                    "value)"))
                break
            if _rebinds(s, name):
                break


def _hazard_modules(cb: Codebase) -> List[str]:
    """Modules whose source mentions shard_map — the donation-caveat
    surface (DESIGN-DCN.md)."""
    out = []
    for mod in cb.iter_modules():
        if "shard_map(" in mod.source or \
                "from jax.experimental.shard_map" in mod.source or \
                "shard_map_compat" in mod.source:
            out.append(mod.rel)
    return out


def run(cb: Codebase) -> List[Violation]:
    violations: List[Violation] = []
    hazard = set(_hazard_modules(cb))
    for mod in cb.iter_modules():
        bindings = _donating_bindings(mod.tree)
        funcs, chains = core.enclosing_chains(mod.tree)
        # rule 1: use-after-donation at call sites of donating entries
        if bindings:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                key = _call_key(node)
                if key is None or key not in bindings:
                    continue
                donated = [node.args[i].id for i in bindings[key]
                           if i < len(node.args)
                           and isinstance(node.args[i], ast.Name)]
                chain = chains.get(id(node), [])
                if donated and chain:
                    _check_use_after(chain[-1], node, donated,
                                     mod.rel, violations)
        # rule 2: knob-routed donation in shard_map hazard modules
        if mod.rel not in hazard:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = core.call_name(node)
            if cname == "build_folded_step":
                # the shared engine donates the carry by default: in
                # a hazard module the opt-in must be spelled out
                if not any(k.arg == "donate_carry"
                           for k in node.keywords):
                    violations.append(Violation(
                        mod.rel, node.lineno,
                        "build_folded_step call relies on the "
                        "implicit donate_carry=True default in a "
                        "shard_map module — spell the opt-in out "
                        "(donate_carry=...) so the DESIGN-DCN.md "
                        "donation caveat has a visible switch"))
                continue
            if cname not in _JIT_NAMES:
                continue
            kw = _donation_kwarg(node)
            if kw is None:
                continue
            if _literal_positions(kw.value) is None:
                continue    # computed from a gate — the knob in action
            chain = chains.get(id(node), [])
            if not any("donate_carry" in [a.arg for a in
                                          fn.args.args + fn.args.kwonlyargs]
                       for fn in chain):
                violations.append(Violation(
                    mod.rel, node.lineno,
                    "literal donate_argnums in a shard_map module — "
                    "this container's jaxlib corrupts buffers donated "
                    "through shard_map manual collectives "
                    "(DESIGN-DCN.md); route the decision through a "
                    "donate_carry= parameter so the caveat has one "
                    "opt-in switch"))
    return violations
