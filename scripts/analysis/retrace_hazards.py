"""retrace-hazards pass: statically catch the silent-retrace bug
class the runtime sentinel (``framework.dispatch.guarded_jit``)
catches dynamically (DESIGN-ANALYSIS.md §retrace-hazards).

A jit program retraces when dispatch N+1's arguments are
*equivalent but unequal* to dispatch N's — the program runs the same
math twice as fast as it recompiles.  Two statically visible sources:

1. **Non-canonical PartitionSpec literals.**  jit canonicalizes its
   output NamedShardings (trailing ``None`` entries dropped, size-1
   mesh axes normalized away); a hand-built ``P('dp', None)`` on the
   *input* side compares unequal to the canonical ``P('dp')`` the
   previous dispatch produced, misses the cache, and retraces once
   after dispatch 1 (the PR-11/PR-15 recompile-pin bug class).
   Flagged: ``P(...)`` / ``PartitionSpec(...)`` literals with a
   trailing ``None`` positional, and ``Mesh(...)`` built from a
   ``reshape`` with a literal size-1 axis.
2. **Fresh-tree ``device_put`` outside the placement seams.**  In the
   training-engine modules every value entering a compiled entry must
   flow through the engine's canonicalizing seam (``_shard`` /
   ``_place``) so its sharding/commitment matches what dispatch 1
   compiled against; an ad-hoc ``jax.device_put`` elsewhere builds a
   fresh tree whose placement the cache has never seen.  Serving
   modules are exempt: their per-dispatch ``device_put`` calls stage
   fresh host data under the engine's pinned default device, which is
   the sanctioned pattern there (engine.py's placement-scope note).
"""

from __future__ import annotations

import ast
import os
from typing import List

from . import core
from .core import Codebase, Violation

NAME = "retrace-hazards"
OK_MESSAGE = ("retrace-hazard check OK: no non-canonical spec "
              "literals; engine device_puts stay in their seams")
REPORT_HEADER = "retrace-hazard violations:"

_SPEC_NAMES = {"P", "PartitionSpec"}

# training-engine modules under rule 2, and the placement-seam
# functions (enclosing chain) where device_put is the point
ENGINE_MODULES = [
    os.path.join("framework", "dispatch.py"),
    os.path.join("distributed", "runner.py"),
    os.path.join("distributed", "fleet", "meta_parallel",
                 "pipeline_parallel.py"),
    os.path.join("hapi", "model.py"),
]

# (module parts..., enclosing function) → why placement is legitimate
ALLOWED_PLACEMENT = {
    ("distributed", "runner.py", "_shard"):
        "THE explicit-dp placement seam: every engine value is "
        "device_put here with its canonical (trailing-None-free) "
        "spec, once, at place() time",
    ("distributed", "fleet", "meta_parallel", "pipeline_parallel.py",
     "_place"):
        "the pipeline engine's placement seam: specs are "
        "canonicalized by strip() the way jit canonicalizes output "
        "NamedShardings before the one-time device_put",
    ("hapi", "model.py", "_train_batch_folded_mesh"):
        "one-time replicated init of the device metric accumulators, "
        "pinned to P() up front precisely so dispatch 2's sharding "
        "matches dispatch 1's compiled layout",
}


def _is_spec_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _SPEC_NAMES
    if isinstance(f, ast.Attribute):
        return f.attr == "PartitionSpec"
    return False


def _trailing_none(call: ast.Call) -> bool:
    if not call.args or call.keywords:
        return False
    last = call.args[-1]
    return isinstance(last, ast.Constant) and last.value is None


def _is_device_put(call: ast.Call) -> bool:
    return core.call_name(call) == "device_put"


def _mesh_size1_axis(call: ast.Call) -> bool:
    """Mesh(x.reshape(..., 1, ...), ...) — a literal size-1 mesh axis:
    specs naming that axis compare unequal to the canonical form that
    drops it, the same cache-miss mode as a trailing None."""
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        getattr(f, "id", "")
    if name != "Mesh" or not call.args:
        return False
    shape_arg = call.args[0]
    if isinstance(shape_arg, ast.Call) and \
            isinstance(shape_arg.func, ast.Attribute) and \
            shape_arg.func.attr == "reshape":
        return any(isinstance(a, ast.Constant) and a.value == 1
                   for a in shape_arg.args)
    return False


def run(cb: Codebase) -> List[Violation]:
    violations: List[Violation] = []
    # rule 1: everywhere in the package
    for mod in cb.iter_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_spec_call(node) and _trailing_none(node):
                violations.append(Violation(
                    mod.rel, node.lineno,
                    "PartitionSpec literal with a trailing None — "
                    "equivalent but UNEQUAL to the canonical spec jit "
                    "produces, so a placed value built from it misses "
                    "the jit cache and silently retraces (drop the "
                    "trailing None)"))
            elif _mesh_size1_axis(node):
                violations.append(Violation(
                    mod.rel, node.lineno,
                    "Mesh built with a literal size-1 axis — specs "
                    "naming it normalize away in jit output "
                    "shardings and stop matching the input specs "
                    "(drop the axis or size it from the device "
                    "count)"))
    # rule 2: engine modules only
    seen_funcs = set()
    for rel in ENGINE_MODULES:
        repo_rel = os.path.join(core.PKG_REL, rel)
        mod = cb.get(repo_rel)
        if mod is None:
            continue
        parts = tuple(rel.split(os.sep))
        funcs, chains = core.enclosing_chains(mod.tree)
        for fn in funcs:
            seen_funcs.add(parts + (fn.name,))
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and _is_device_put(node)):
                continue
            chain = chains.get(id(node), [])
            if not any(parts + (fn.name,) in ALLOWED_PLACEMENT
                       for fn in chain):
                where = f"in {chain[-1].name}()" if chain \
                    else "at module level"
                violations.append(Violation(
                    repo_rel, node.lineno,
                    f"device_put {where} outside the engine's "
                    "placement seams — an ad-hoc placement builds a "
                    "tree whose sharding/commitment the compiled "
                    "entry has never seen (route through "
                    "_shard/_place, or stage via io/staging)"))
    for entry, reason in ALLOWED_PLACEMENT.items():
        if entry not in seen_funcs:
            violations.append(Violation(
                os.path.join(core.PKG_REL, *entry[:-1]), 0,
                f"stale placement-seam entry: no function named "
                f"{entry[-1]!r} ({reason[:40]}...)"))
    return violations
