"""host-sync pass: no host<->device sync in the hot-loop modules
outside whitelisted points (DESIGN-PERF.md; ported verdict-unchanged
from scripts/check_host_sync.py — that script is now a thin wrapper).

The async-dispatch contract says the ``Model.fit`` /
``DistributedRunner`` hot loop may NOT synchronize host and device:
every ``jax.device_get`` / ``.numpy()`` / ``np.asarray`` /
``jax.block_until_ready`` on a device value stalls the dispatch queue
and serializes host with device — exactly the overlap TPUs live on.
Syncs are allowed only at explicitly whitelisted points (boundary
materialization, host→device staging of fresh numpy input, public
APIs that return numpy by contract).  The check is syntactic — it
cannot tell a device value from a host value — so every allowlisted
(module, function) carries its justification here, on record.
"""

from __future__ import annotations

import ast
import os
from typing import List

from . import core
from .core import Codebase, Violation

NAME = "host-sync"
OK_MESSAGE = ("host-sync coverage OK: hot-loop modules sync only at "
              "whitelisted points")
REPORT_HEADER = "host-sync violations:"

# the hot-loop modules under the contract (paths relative to the
# package root, as the original script spelled them)
HOT_MODULES = [
    os.path.join("hapi", "model.py"),
    os.path.join("hapi", "callbacks.py"),
    os.path.join("hapi", "train_state.py"),
    os.path.join("distributed", "runner.py"),
    # the explicit dp gradient path (DESIGN-DCN.md): the compressed
    # ring collectives and the sharded weight update trace INSIDE the
    # compiled step — a host sync here would stall every dispatch
    os.path.join("distributed", "compressed.py"),
    os.path.join("metric", "__init__.py"),
    os.path.join("io", "dataloader.py"),
    os.path.join("io", "staging.py"),
    os.path.join("framework", "lazy.py"),
    # the unified dispatch engine (DESIGN-PERF.md §Unified dispatch
    # engine): grouping + auto-K sit directly on the hot loop for
    # both the single-chip and mesh paths
    os.path.join("framework", "dispatch.py"),
    # serving decode hot path (DESIGN-SERVING.md): the persistent
    # dispatch loop must never stall host↔device — same contract,
    # same guard, as the training loop
    os.path.join("inference", "serving", "engine.py"),
    os.path.join("inference", "serving", "ragged_attention.py"),
    os.path.join("inference", "serving", "kv_cache.py"),
    os.path.join("inference", "serving", "decode_model.py"),
    os.path.join("inference", "serving", "scheduler.py"),
    # long-context tier (DESIGN-SERVING.md §Long-context tier): the
    # fused paged-attention kernel and the sampling math trace INSIDE
    # the compiled decode step; the prefix cache is host bookkeeping
    # living on the pump thread between dispatches — none of the
    # three may ever sync host with device
    os.path.join("inference", "serving", "paged_attention_kernel.py"),
    os.path.join("inference", "serving", "sampling.py"),
    os.path.join("inference", "serving", "prefix_cache.py"),
    # disaggregated tier (DESIGN-SERVING.md §Disaggregated tier):
    # page migration is a jitted device-to-device gather/scatter cut
    # and imported ON the pump threads — the ticket itself is host
    # bookkeeping and must stay that way (reading migrated K/V on the
    # host would stall both replicas' dispatch queues at once); the
    # disagg router runs its transition hook on prefill pump threads
    os.path.join("inference", "serving", "migration.py"),
    os.path.join("inference", "serving", "disagg.py"),
    # speculative tier (DESIGN-SERVING.md §Speculative tier): the
    # draft/verify/accept-reject window traces INSIDE the compiled
    # decode step — acceptance counting on the host would sync every
    # dispatch and erase the whole multi-token win
    os.path.join("inference", "serving", "spec_decode.py"),
    # observability subsystem (DESIGN-OBSERVABILITY.md): it lives
    # INSIDE every hot loop above, so it is held to the same contract
    # — instruments hold lazy device values and defer the sync to
    # scrape (metrics._materialize is a float() call, deliberately
    # not a whitelisted jax sync: a device value pays its sync via
    # the LazyScalar.__float__ sanctioned path)
    os.path.join("observability", "__init__.py"),
    os.path.join("observability", "trace.py"),
    os.path.join("observability", "metrics.py"),
    os.path.join("observability", "export.py"),
    # distributed observability plane (DESIGN-OBSERVABILITY.md
    # §Distributed plane): the HTTP handlers and the fleet merge run
    # next to live training/serving processes — materialization is
    # allowed ONLY inside a scrape request (which rides the same
    # metrics._materialize float() path as in-process scrape), and
    # the aggregator works on already-materialized snapshot dicts, so
    # neither module may contain a direct jax/numpy sync call at all
    os.path.join("observability", "http.py"),
    os.path.join("observability", "aggregate.py"),
    # action loop (DESIGN-OBSERVABILITY.md §Action loop): the serving
    # router's control loop and the decision ring run NEXT TO the
    # decode hot loop they supervise — both read host state only
    # (queue depths, host-float histograms via materialize=False), so
    # neither may contain a direct jax/numpy sync call at all
    os.path.join("observability", "events.py"),
    os.path.join("inference", "serving", "router.py"),
    # pipeline-schedule engine on the unified dispatcher (ISSUE 15,
    # DESIGN-PERF.md §Unified dispatch engine): train_batch /
    # train_steps_folded sit directly on the hot loop for pp and
    # hybrid dp x mp x pp meshes — staging rides io/staging, wrapper
    # write-back is reference-only, and nothing may sync host with
    # device between dispatches
    os.path.join("distributed", "fleet", "meta_parallel",
                 "pipeline_parallel.py"),
]

# (module, enclosing function) → why this sync point is legitimate
ALLOWED_SYNC = {
    ("framework", "lazy.py", "_materialize"):
        "THE deferred sync point: LazyScalar materializes on first "
        "host use (callback formatting), not per step",
    ("framework", "lazy.py", "block"):
        "auto-K calibration probe ONLY: waits on the device value "
        "without fetching it, during the first calib_groups "
        "dispatches of a fit — never steady state",
    ("framework", "dispatch.py", "_calibration_block"):
        "auto-K calibration ONLY: splits host dispatch overhead from "
        "device step time over the first calib_groups dispatches; "
        "the steady-state hot loop never enters it",
    ("hapi", "model.py", "predict_batch"):
        "public API returns numpy by contract",
    ("hapi", "model.py", "_cat"):
        "host-side concat of host loader batches (grad-accum "
        "grouping happens before staging)",
    ("hapi", "callbacks.py", "_fmt"):
        "verbose-interval log formatting (ProgBarLogger) — the "
        "sanctioned materialization cadence",
    ("hapi", "callbacks.py", "on_eval_end"):
        "EarlyStopping decision at the epoch boundary",
    ("metric", "__init__.py", "_np"):
        "host-path Metric API: used for direct user calls, never by "
        "the fit hot loop (which uses device_batch_stats)",
    ("metric", "__init__.py", "update"):
        "host-path Metric.update (outside the fit hot loop)",
    ("metric", "__init__.py", "compute"):
        "host-path Metric.compute (outside the fit hot loop)",
    ("metric", "__init__.py", "accumulate"):
        "epoch-boundary materialization of device accumulators",
    ("metric", "__init__.py", "_device_stat_sum"):
        "accumulate()'s helper: one materialization of the pending "
        "stats + folded-carry accumulator at the epoch boundary",
    ("metric", "__init__.py", "accuracy"):
        "functional host metric (one-shot, not a loop)",
    ("io", "staging.py", "to_device_value"):
        "host→device staging (np.asarray views host data, never a "
        "device value)",
    ("io", "staging.py", "to_device_values"):
        "host→device staging (batched device_put of host leaves)",
    ("io", "staging.py", "stack_to_device"):
        "step-folding staging: np.asarray views HOST batch leaves "
        "before the K-group's single batched device_put; device "
        "leaves take jnp.stack (no D2H)",
    ("io", "dataloader.py", "default_collate_fn"):
        "collates host sample arrays produced by the dataset",
    ("inference", "serving", "engine.py", "_poll_done"):
        "THE group-boundary sync of the decode loop: one fetch every "
        "done_poll_interval dispatches, never inside one — [B] bool "
        "done mask classically; widened to the (done, lengths, gen) "
        "triple under speculative decoding, still one device_get at "
        "the same cadence (DESIGN-SERVING.md §EOS, §Speculative tier)",
    ("inference", "serving", "engine.py", "_warmup"):
        "AOT compile timing before traffic cuts over — blocking on "
        "device completion is the point (cold-start metric; `warmup` "
        "wraps this body in the engine's device-placement scope)",
}


def _sync_kind(call: ast.Call):
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr == "device_get":
            return "jax.device_get"
        if f.attr == "block_until_ready":
            return "jax.block_until_ready"
        if f.attr == "numpy" and not call.args and not call.keywords:
            return ".numpy()"
        if f.attr == "asarray" and isinstance(f.value, ast.Name) \
                and f.value.id in ("np", "numpy"):
            return "np.asarray"
    elif isinstance(f, ast.Name) and f.id == "device_get":
        return "jax.device_get"
    return None


def run(cb: Codebase) -> List[Violation]:
    violations: List[Violation] = []
    seen_funcs = set()
    for rel in HOT_MODULES:
        repo_rel = os.path.join(core.PKG_REL, rel)
        mod = cb.get(repo_rel)
        if mod is None:
            broken = cb.broken.get(repo_rel)
            violations.append(Violation(
                repo_rel, broken[0] if broken else 0,
                "hot-loop module missing or unparseable — the "
                "host-sync contract cannot be checked"))
            continue
        parts = tuple(rel.split(os.sep))
        funcs, chains = core.enclosing_chains(mod.tree)
        for fn in funcs:
            seen_funcs.add(parts + (fn.name,))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _sync_kind(node)
            if kind is None:
                continue
            chain = chains.get(id(node), [])
            if not chain:
                violations.append(Violation(
                    repo_rel, node.lineno,
                    f"module-level {kind} (host sync outside any "
                    "whitelisted function)"))
            elif not any(parts + (fn.name,) in ALLOWED_SYNC
                         for fn in chain):
                violations.append(Violation(
                    repo_rel, node.lineno,
                    f"{kind} in {chain[-1].name}() is not a "
                    "whitelisted sync point (DESIGN-PERF.md: the hot "
                    "loop must not stall the dispatch queue)"))
    # a stale allowlist hides future violations: every entry must
    # still name a real function
    for entry, reason in ALLOWED_SYNC.items():
        if entry not in seen_funcs:
            violations.append(Violation(
                os.path.join(core.PKG_REL, *entry[:-1]), 0,
                f"stale allowlist entry: no function named "
                f"{entry[-1]!r} ({reason[:40]}...)"))
    return violations
