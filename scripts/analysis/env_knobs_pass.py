"""env-knobs pass: every ``PADDLE_TPU_*`` environment read resolves
through the central registry (``paddle_tpu/framework/env_knobs.py``)
and the registry itself stays live and documented
(DESIGN-ANALYSIS.md §env-knobs).

Rules:

1. **No direct reads of the prefix.**  ``os.environ.get(...)`` /
   ``os.environ[...]`` / ``os.getenv(...)`` of a ``PADDLE_TPU_*``
   name anywhere outside ``env_knobs.py`` is a violation — those
   reads are exactly the scattered, undocumented knobs the registry
   exists to end.  Names are resolved through module-level string
   constants (``_DP_COMPRESS_ENV = "PADDLE_TPU_..."``).  Writes
   (``env["PADDLE_TPU_X"] = ...``, subprocess env dicts) are exempt:
   handing a knob to a child process is wiring, not reading.
2. **Registered names only.**  A literal name passed to
   ``env_knobs.get_raw/get_bool/get_int/get_float`` must be in the
   registry (the accessors also enforce this at runtime with
   KeyError); a *computed* name defeats the census and is rejected.
3. **No dead registry entries.**  Every registered knob's name must
   appear in production wiring — ``paddle_tpu/`` or the bench A/B
   harness (``bench.py``, ``scripts/tpu_ab.py``) — as a string
   literal.  An entry nothing mentions is documentation rot.
4. **README freshness.**  The block between the
   ``<!-- env-knobs:begin -->`` / ``<!-- env-knobs:end -->`` markers
   must equal ``env_knobs.render_table()`` output (regenerate with
   ``python scripts/lint.py --write-env-table``).
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional, Set

from . import core
from .core import Codebase, Violation

NAME = "env-knobs"
OK_MESSAGE = ("env-knob coverage OK: every PADDLE_TPU_* read resolves "
              "through the registry, every entry is wired, README "
              "table fresh")
REPORT_HEADER = "env-knob violations:"

PREFIX = "PADDLE_TPU_"
REGISTRY_MOD = os.path.join(core.PKG_REL, "framework", "env_knobs.py")
_ACCESSORS = {"get_raw", "get_bool", "get_int", "get_float"}

BEGIN_MARK = "<!-- env-knobs:begin -->"
END_MARK = "<!-- env-knobs:end -->"


def load_registry() -> Dict[str, object]:
    """The KNOBS dict, loaded straight from the file — stdlib-only by
    design, so no package import (and no jax) is paid here."""
    path = os.path.join(core.REPO, REGISTRY_MOD)
    spec = importlib.util.spec_from_file_location("_env_knobs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return dict(mod.KNOBS), mod.render_table()


def _env_read_name(node: ast.Call, consts: Dict[str, str]
                   ) -> Optional[str]:
    """The knob name read by an ``os.environ.get`` / ``os.getenv``
    call, resolved through module constants; None if not an env
    read."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "getenv":
            pass
        elif f.attr == "get" and isinstance(f.value, ast.Attribute) \
                and f.value.attr == "environ":
            pass
        elif f.attr == "get" and isinstance(f.value, ast.Name) \
                and f.value.id == "environ":
            pass
        else:
            return None
    else:
        return None
    if not node.args:
        return None
    return _resolve(node.args[0], consts)


def _resolve(node: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    val = core.const_str(node)
    if val is not None:
        return val
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _mentioned_names(mod) -> Set[str]:
    """Every PADDLE_TPU_* string literal in the module's AST — the
    wiring census for rule 3."""
    out = set()
    for node in ast.walk(mod.tree):
        val = core.const_str(node)
        if val is not None and val.startswith(PREFIX):
            out.add(val)
    return out


def run(cb: Codebase, registry=None) -> List[Violation]:
    if registry is None:
        knobs, table = load_registry()
    else:
        knobs, table = registry
    violations: List[Violation] = []
    wired: Set[str] = set()
    for mod in sorted(cb.modules.values(), key=lambda m: m.rel):
        is_registry = mod.rel == REGISTRY_MOD
        if not is_registry:
            wired |= _mentioned_names(mod)
        consts = core.module_str_constants(mod.tree)
        for node in ast.walk(mod.tree):
            # rule 1: direct env reads of the prefix
            if isinstance(node, ast.Call) and not is_registry:
                name = _env_read_name(node, consts)
                if name and name.startswith(PREFIX):
                    violations.append(Violation(
                        mod.rel, node.lineno,
                        f"direct os.environ read of {name} — resolve "
                        "through framework.env_knobs (the registry is "
                        "the one place a knob's name/default/doc "
                        "live)"))
            if isinstance(node, ast.Subscript) and not is_registry \
                    and isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "environ":
                name = _resolve(node.slice, consts)
                if name and name.startswith(PREFIX):
                    violations.append(Violation(
                        mod.rel, node.lineno,
                        f"direct os.environ[{name!r}] read — resolve "
                        "through framework.env_knobs"))
            # rule 2: accessor names must be registered literals
            if isinstance(node, ast.Call) and not is_registry and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _ACCESSORS and \
                    isinstance(node.func.value, ast.Name) and \
                    "env_knobs" in node.func.value.id and node.args:
                name = _resolve(node.args[0], consts)
                if name is None:
                    violations.append(Violation(
                        mod.rel, node.lineno,
                        f"computed knob name passed to env_knobs."
                        f"{node.func.attr}() — knob reads must be "
                        "statically auditable literals"))
                elif name not in knobs:
                    violations.append(Violation(
                        mod.rel, node.lineno,
                        f"{name} is not in the env_knobs registry — "
                        "register it (name/default/doc) or fix the "
                        "typo (get_raw would raise KeyError at "
                        "runtime)"))
    # rule 3: dead registry entries
    for name in sorted(knobs):
        if name not in wired:
            violations.append(Violation(
                REGISTRY_MOD, 0,
                f"registered knob {name} has no production wiring — "
                "nothing in paddle_tpu/ or the bench harness mentions "
                "it (dead entry, or the consumer was removed)"))
    # rule 4: README table freshness
    readme = cb.texts.get("README.md")
    if readme is not None:
        if BEGIN_MARK not in readme or END_MARK not in readme:
            violations.append(Violation(
                "README.md", 0,
                f"missing env-knob table markers ({BEGIN_MARK} / "
                f"{END_MARK}) — run python scripts/lint.py "
                "--write-env-table"))
        else:
            start = readme.index(BEGIN_MARK) + len(BEGIN_MARK)
            end = readme.index(END_MARK)
            current = readme[start:end].strip("\n")
            if current != table.strip("\n"):
                line = readme[:readme.index(BEGIN_MARK)].count("\n") + 1
                violations.append(Violation(
                    "README.md", line,
                    "env-knob table is stale (registry and README "
                    "disagree) — regenerate with python "
                    "scripts/lint.py --write-env-table"))
    return violations


def write_env_table(repo: str = core.REPO) -> bool:
    """Regenerate the README block between the markers; returns True
    when the file changed."""
    _, table = load_registry()
    path = os.path.join(repo, "README.md")
    with open(path) as fh:
        readme = fh.read()
    block = f"{BEGIN_MARK}\n{table}{END_MARK}"
    if BEGIN_MARK in readme and END_MARK in readme:
        start = readme.index(BEGIN_MARK)
        end = readme.index(END_MARK) + len(END_MARK)
        new = readme[:start] + block + readme[end:]
    else:
        section = (
            "\n## Environment knobs\n\n"
            "Every `PADDLE_TPU_*` variable the package reads, "
            "generated from the registry\n"
            "(`paddle_tpu/framework/env_knobs.py`) by `python "
            "scripts/lint.py --write-env-table`;\n"
            "the `env-knobs` lint pass fails when this table goes "
            "stale.\n\n" + block + "\n")
        new = readme.rstrip("\n") + "\n" + section
    if new != readme:
        with open(path, "w") as fh:
            fh.write(new)
        return True
    return False
