"""Shared pass framework for the program-stability analysis suite
(DESIGN-ANALYSIS.md).

Every static check in ``scripts/analysis/`` runs over ONE
:class:`Codebase`: one file walk, one ``ast.parse`` per module, with
per-line ``# lint: allow(<pass>): <reason>`` suppressions collected up
front so each pass reports violations uniformly and the suppression
ledger (who silenced what, and why) stays on record.

A pass is a module with two attributes:

* ``NAME`` — kebab-case pass name (what ``allow(...)`` keys on),
* ``run(cb: Codebase) -> List[Violation]`` — the check itself.

``run_pass`` applies suppressions; ``scripts/lint.py`` additionally
enforces suppression hygiene (reason required, pass name must exist,
unused suppressions are themselves violations).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
PKG_REL = "paddle_tpu"

# Modules outside paddle_tpu/ that wire env knobs (bench A/B harness);
# README.md rides along as text for the staleness check.
EXTRA_MODULES = ("bench.py", os.path.join("scripts", "tpu_ab.py"))
TEXT_FILES = ("README.md",)

# same-line suppression: ``code  # lint: allow(pass-name): reason``
SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\(([a-z0-9_-]+)\)(?::\s*(.*\S))?")


class Violation(NamedTuple):
    rel: str          # path relative to the repo root
    line: int
    message: str
    pass_name: str = ""


class Suppression:
    __slots__ = ("rel", "line", "pass_name", "reason", "used")

    def __init__(self, rel: str, line: int, pass_name: str,
                 reason: Optional[str]):
        self.rel = rel
        self.line = line
        self.pass_name = pass_name
        self.reason = reason
        self.used = False


class Module:
    """One parsed production module: source, AST, suppressions."""

    __slots__ = ("rel", "source", "tree", "suppressions")

    def __init__(self, rel: str, source: str, tree: ast.Module):
        self.rel = rel
        self.source = source
        self.tree = tree
        self.suppressions: List[Suppression] = []
        for i, text in enumerate(source.splitlines(), start=1):
            m = SUPPRESS_RE.search(text)
            if m:
                self.suppressions.append(
                    Suppression(rel, i, m.group(1), m.group(2)))


class Codebase:
    """The one-walk, one-parse-per-module view every pass shares."""

    def __init__(self, modules: Dict[str, Module],
                 broken: Dict[str, Tuple[int, str]],
                 texts: Dict[str, str], repo: str = REPO):
        self.modules = modules
        self.broken = broken        # rel -> (lineno, syntax-error msg)
        self.texts = texts
        self.repo = repo

    @classmethod
    def load(cls, repo: str = REPO) -> "Codebase":
        modules: Dict[str, Module] = {}
        broken: Dict[str, Tuple[int, str]] = {}
        pkg = os.path.join(repo, PKG_REL)
        paths = []
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            paths.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
        paths.extend(os.path.join(repo, rel) for rel in EXTRA_MODULES)
        for path in paths:
            if not os.path.exists(path):
                continue
            rel = os.path.relpath(path, repo)
            with open(path) as fh:
                source = fh.read()
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                broken[rel] = (e.lineno or 0, e.msg or "syntax error")
                continue
            modules[rel] = Module(rel, source, tree)
        texts = {}
        for rel in TEXT_FILES:
            path = os.path.join(repo, rel)
            if os.path.exists(path):
                with open(path) as fh:
                    texts[rel] = fh.read()
        return cls(modules, broken, texts, repo)

    @classmethod
    def from_sources(cls, sources: Dict[str, str],
                     texts: Optional[Dict[str, str]] = None
                     ) -> "Codebase":
        """Synthetic codebase for the negative-control tests: map of
        repo-relative path -> python source."""
        modules: Dict[str, Module] = {}
        broken: Dict[str, Tuple[int, str]] = {}
        for rel, source in sources.items():
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError as e:
                broken[rel] = (e.lineno or 0, e.msg or "syntax error")
                continue
            modules[rel] = Module(rel, source, tree)
        return cls(modules, broken, dict(texts or {}), repo=REPO)

    # -- access ----------------------------------------------------------
    def get(self, rel: str) -> Optional[Module]:
        return self.modules.get(rel)

    def iter_modules(self, prefix: str = PKG_REL + os.sep
                     ) -> Iterator[Module]:
        for rel in sorted(self.modules):
            if rel.startswith(prefix):
                yield self.modules[rel]

    def all_suppressions(self) -> Iterator[Suppression]:
        for rel in sorted(self.modules):
            yield from self.modules[rel].suppressions

    def suppressions_at(self, rel: str, line: int, pass_name: str
                        ) -> List[Suppression]:
        mod = self.modules.get(rel)
        if mod is None:
            return []
        return [s for s in mod.suppressions
                if s.line == line and s.pass_name == pass_name]


# -- shared AST helpers ------------------------------------------------------

def call_name(call: ast.Call) -> str:
    """Terminal name of a call: ``f(...)`` / ``obj.f(...)`` -> 'f'."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    return getattr(f, "id", "")


def enclosing_chains(tree: ast.Module) -> Tuple[list, Dict[int, list]]:
    """All function defs plus ``id(node) -> [enclosing functions]``
    (outermost first, innermost last) — the one walk every
    function-scoped rule shares."""
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    chains: Dict[int, list] = {}
    for fn in funcs:
        for n in ast.walk(fn):
            chains.setdefault(id(n), []).append(fn)
    return funcs, chains


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings (e.g. the
    ``_DP_COMPRESS_ENV = "PADDLE_TPU_DP_COMPRESS"`` idiom)."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            val = const_str(node.value)
            if val is not None:
                out[node.targets[0].id] = val
    return out


# -- runner ------------------------------------------------------------------

def run_pass(cb: Codebase, pass_mod) -> List[Violation]:
    """Run one pass and apply same-line suppressions (marking them
    used).  Suppression *hygiene* is lint.py's job, not the pass's."""
    out: List[Violation] = []
    for v in pass_mod.run(cb):
        sups = cb.suppressions_at(v.rel, v.line, pass_mod.NAME)
        if sups:
            for s in sups:
                s.used = True
        else:
            out.append(v._replace(pass_name=pass_mod.NAME))
    return out


def suppression_violations(cb: Codebase, known_passes,
                           ran_passes) -> List[Violation]:
    """The suppression ledger's own rules: every ``allow`` names a real
    pass, carries a reason, and silences something that still fires."""
    out: List[Violation] = []
    ran = set(ran_passes)
    for s in cb.all_suppressions():
        if s.pass_name not in known_passes:
            out.append(Violation(
                s.rel, s.line,
                f"lint: allow({s.pass_name}) names an unknown pass "
                f"(known: {', '.join(sorted(known_passes))})",
                "suppressions"))
            continue
        if not s.reason:
            out.append(Violation(
                s.rel, s.line,
                f"lint: allow({s.pass_name}) has no reason — every "
                "suppression carries its justification on record",
                "suppressions"))
        if s.pass_name in ran and not s.used:
            out.append(Violation(
                s.rel, s.line,
                f"unused suppression: allow({s.pass_name}) silences "
                "nothing the pass still reports — remove it",
                "suppressions"))
    return out


def format_report(violations: List[Violation]) -> str:
    lines = []
    for v in violations:
        tag = f" [{v.pass_name}]" if v.pass_name else ""
        lines.append(f"  {v.rel}:{v.line}: {v.message}{tag}")
    return "\n".join(lines)
