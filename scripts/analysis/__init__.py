"""Program-stability analysis suite (DESIGN-ANALYSIS.md).

Eight passes over one shared :class:`core.Codebase`; run them all via
``python scripts/lint.py`` or individually through the thin
``scripts/check_*.py`` wrappers (kept for their historic CLIs).
"""

from . import core  # noqa: F401
from . import (donation_safety, env_knobs_pass, fault_sites,  # noqa: F401
               host_sync, knob_consumption, metric_names,
               retrace_hazards, retry_coverage)

# registration order is report order: the four ported checks first,
# then the program-stability passes this suite added
PASSES = {m.NAME: m for m in (
    host_sync,
    metric_names,
    fault_sites,
    retry_coverage,
    retrace_hazards,
    donation_safety,
    knob_consumption,
    env_knobs_pass,
)}
