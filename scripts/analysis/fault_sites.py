"""fault-sites pass: FaultPlan site-name registry coverage
(DESIGN-RESILIENCE.md; ported verdict-unchanged from
scripts/check_fault_sites.py).

Chaos rules target injection sites by *string name*; a typo on either
side produces an injection point that silently never fires — the
recovery path looks chaos-tested while nothing is being injected.

1. every string-literal site passed to ``fault_point(...)`` /
   ``should_drop(...)`` in production code must appear in the central
   registry (``resilience.faults.KNOWN_SITES``);
2. every registry name must be wired into at least one production
   call site (a registry entry with zero call sites is a recovery
   path whose chaos coverage silently evaporated);
3. call sites must use a string literal — a computed site name can't
   be audited and defeats the registry.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Set

from . import core
from .core import Codebase, Violation

NAME = "fault-sites"
OK_MESSAGE = ("fault-site coverage OK: every injection site is "
              "registered and every registered site is wired")
REPORT_HEADER = "fault-site violations:"

_INJECT_FNS = {"fault_point", "should_drop"}

REGISTRY_MOD = os.path.join(core.PKG_REL, "distributed", "resilience",
                            "faults.py")


def _known_sites() -> Set[str]:
    sys.path.insert(0, core.REPO)
    try:
        from paddle_tpu.distributed.resilience.faults import KNOWN_SITES
    finally:
        sys.path.pop(0)
    return set(KNOWN_SITES)


def _iter_sites(cb: Codebase):
    """Yield (repo_rel, lineno, site|None) for every injection call in
    the package; site is None when the first arg is not a literal."""
    for mod in cb.iter_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if core.call_name(node) not in _INJECT_FNS:
                continue
            if not node.args:
                continue
            site = core.const_str(node.args[0])
            yield mod.rel, node.lineno, site


def run(cb: Codebase, known_sites: Set[str] = None) -> List[Violation]:
    """``known_sites`` overrides the runtime registry import so the
    negative-control tests don't need a fake package on sys.path."""
    if known_sites is None:
        known_sites = _known_sites()
    violations: List[Violation] = []
    used: Set[str] = set()
    for rel, line, site in _iter_sites(cb):
        # the registry's own module defines the names, it doesn't
        # call them
        if rel == REGISTRY_MOD:
            continue
        if site is None:
            violations.append(Violation(
                rel, line, "injection site is not a string literal "
                "(unauditable; name sites statically)"))
        elif site not in known_sites:
            violations.append(Violation(
                rel, line, f"unknown fault site {site!r} — add it to "
                "resilience.faults.KNOWN_SITES or fix the typo"))
        else:
            used.add(site)
    for site in sorted(known_sites - used):
        violations.append(Violation(
            REGISTRY_MOD, 0,
            f"registered fault site {site!r} has no production call "
            "site — dead registry entry or a typo'd call"))
    return violations
