"""metric-names pass: instrument-name convention on the process-wide
registry (DESIGN-OBSERVABILITY.md §Metric naming convention; ported
verdict-unchanged from scripts/check_metric_names.py).

Enforced at the AST level over every production module:

- **Literal names only.**  A computed name (f-string, concat,
  variable) cannot be grepped from a dashboard back to its call site
  and silently mints unbounded families (``labels`` carry the dynamic
  dimension instead).
- **Shape:** snake_case, ``^[a-z][a-z0-9_]*[a-z0-9]$``, no ``__``.
- **Counters end in ``_total``**; **histograms end in a unit suffix**
  (``_s``, ``_ms``, ``_bytes``, ``_pct``, ``_ratio``,
  ``_per_dispatch``); **gauges never end in ``_total``**.

Receiver heuristic (syntactic): ``registry().counter(...)``,
``reg.counter(...)`` or ``self._reg.counter(...)``.  The check fails
closed on its own coverage: implausibly few matched call sites means
the heuristic broke, and that is itself a violation.
"""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from .core import Codebase, Violation

NAME = "metric-names"
OK_MESSAGE = "metric-name convention OK"
REPORT_HEADER = "metric-name violations:"

KINDS = ("counter", "gauge", "histogram")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*[a-z0-9]$")
UNIT_SUFFIXES = ("_s", "_ms", "_bytes", "_pct", "_ratio",
                 "_per_dispatch")

# fewer literal call sites than this means the receiver heuristic
# stopped matching the codebase idiom — fail loudly, not silently
# (52 sites as of PR 13's control-loop instruments; the floor trails
# the census so genuine removals don't trip it)
MIN_EXPECTED_SITES = 40


def _is_registry_receiver(node: ast.expr) -> bool:
    """registry() / *.registry() / reg / self._reg / *_reg"""
    if isinstance(node, ast.Call):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        return name == "registry"
    if isinstance(node, ast.Name):
        return node.id == "reg" or node.id.endswith("_reg")
    if isinstance(node, ast.Attribute):
        return node.attr == "_reg" or node.attr.endswith("_reg")
    return False


def _check_name(kind: str, name: str) -> List[str]:
    problems = []
    if not NAME_RE.match(name) or "__" in name:
        problems.append(f"{name!r} is not snake_case "
                        "([a-z][a-z0-9_]*, no '__')")
        return problems
    if kind == "counter" and not name.endswith("_total"):
        problems.append(f"counter {name!r} must end in _total")
    if kind == "histogram" and not name.endswith(UNIT_SUFFIXES):
        problems.append(
            f"histogram {name!r} must end in a unit suffix "
            f"{UNIT_SUFFIXES}")
    if kind != "counter" and name.endswith("_total"):
        problems.append(
            f"{kind} {name!r} must not end in _total (that suffix "
            "promises a monotone counter)")
    return problems


def scan(cb: Codebase) -> Tuple[List[Violation], int]:
    """(violations, matched call sites) — the wrapper CLI reports the
    site count; ``run`` folds the coverage self-check in."""
    violations: List[Violation] = []
    sites = 0
    for rel, (lineno, msg) in sorted(cb.broken.items()):
        if rel.startswith("paddle_tpu"):
            violations.append(Violation(rel, lineno,
                                        f"unparseable: {msg}"))
    for mod in cb.iter_modules():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in KINDS
                    and _is_registry_receiver(node.func.value)):
                continue
            sites += 1
            if not node.args:
                violations.append(Violation(
                    mod.rel, node.lineno,
                    f".{node.func.attr}() with no name argument"))
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                violations.append(Violation(
                    mod.rel, node.lineno,
                    f".{node.func.attr}() name is computed "
                    f"({ast.dump(arg)[:60]}...): instrument "
                    "names must be string literals — put the "
                    "dynamic dimension in labels"))
                continue
            for p in _check_name(node.func.attr, arg.value):
                violations.append(Violation(mod.rel, node.lineno, p))
    if sites < MIN_EXPECTED_SITES:
        violations.append(Violation(
            "scripts/analysis/metric_names.py", 0,
            f"coverage self-check: only {sites} registry call sites "
            f"matched (expected >= {MIN_EXPECTED_SITES}) — the "
            "receiver heuristic no longer matches the codebase "
            "idiom"))
    return violations, sites


def run(cb: Codebase) -> List[Violation]:
    return scan(cb)[0]
