#!/usr/bin/env python
"""Static metric-name convention check (DESIGN-OBSERVABILITY.md
§Metric naming convention).

Every instrument on the process-wide registry is created through
``registry().counter/gauge/histogram("name", ...)`` — this check
walks every production module under ``paddle_tpu/`` and enforces, at
the AST level:

- **Literal names only.**  A computed name (f-string, concat,
  variable) cannot be grepped from a dashboard back to its call site
  and silently mints unbounded families; the registry's
  one-name-one-meaning contract needs names that exist in the source
  text.  (``labels`` carry the dynamic dimension instead.)
- **Shape:** snake_case, ``^[a-z][a-z0-9_]*[a-z0-9]$``, no ``__``.
- **Counters end in ``_total``** (Prometheus counter convention).
- **Histograms end in a unit suffix** (``_s``, ``_ms``, ``_bytes``,
  ``_pct``, ``_ratio``) — every histogram in the process is a
  distribution *of* something measurable on a shared grid.
- **Gauges never end in ``_total``** (that suffix promises
  monotonicity) and carry a unit suffix when they measure a unit
  (level quantities like ``serving_queue_depth`` stay bare).

Receiver heuristic (syntactic, like check_host_sync.py): a call is a
registry call when it reads ``registry().counter(...)``,
``reg.counter(...)`` or ``self._reg.counter(...)`` — the three idioms
the codebase uses (``jnp.histogram`` and friends don't match).  The
check fails closed on its own coverage: finding implausibly few call
sites means the heuristic broke, and that is itself a violation.

Mirrors check_retry_coverage/check_fault_sites/check_host_sync:
enforced as a plain test, exit 0 clean / 1 with a report.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddle_tpu")

KINDS = ("counter", "gauge", "histogram")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*[a-z0-9]$")
UNIT_SUFFIXES = ("_s", "_ms", "_bytes", "_pct", "_ratio")

# fewer literal call sites than this means the receiver heuristic
# stopped matching the codebase idiom — fail loudly, not silently
# (52 sites as of PR 13's control-loop instruments; the floor trails
# the census so genuine removals don't trip it)
MIN_EXPECTED_SITES = 40


def _is_registry_receiver(node: ast.expr) -> bool:
    """registry() / *.registry() / reg / self._reg / *_reg"""
    if isinstance(node, ast.Call):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        return name == "registry"
    if isinstance(node, ast.Name):
        return node.id == "reg" or node.id.endswith("_reg")
    if isinstance(node, ast.Attribute):
        return node.attr == "_reg" or node.attr.endswith("_reg")
    return False


def _check_name(kind: str, name: str) -> List[str]:
    problems = []
    if not NAME_RE.match(name) or "__" in name:
        problems.append(f"{name!r} is not snake_case "
                        "([a-z][a-z0-9_]*, no '__')")
        return problems
    if kind == "counter" and not name.endswith("_total"):
        problems.append(f"counter {name!r} must end in _total")
    if kind == "histogram" and not name.endswith(UNIT_SUFFIXES):
        problems.append(
            f"histogram {name!r} must end in a unit suffix "
            f"{UNIT_SUFFIXES}")
    if kind != "counter" and name.endswith("_total"):
        problems.append(
            f"{kind} {name!r} must not end in _total (that suffix "
            "promises a monotone counter)")
    return problems


def check() -> Tuple[List[Tuple[str, int, str]], int]:
    violations: List[Tuple[str, int, str]] = []
    sites = 0
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO)
            with open(path) as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError as e:
                    violations.append((rel, e.lineno or 0,
                                       f"unparseable: {e.msg}"))
                    continue
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in KINDS
                        and _is_registry_receiver(node.func.value)):
                    continue
                sites += 1
                if not node.args:
                    violations.append(
                        (rel, node.lineno,
                         f".{node.func.attr}() with no name argument"))
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    violations.append(
                        (rel, node.lineno,
                         f".{node.func.attr}() name is computed "
                         f"({ast.dump(arg)[:60]}...): instrument "
                         "names must be string literals — put the "
                         "dynamic dimension in labels"))
                    continue
                for p in _check_name(node.func.attr, arg.value):
                    violations.append((rel, node.lineno, p))
    if sites < MIN_EXPECTED_SITES:
        violations.append(
            ("scripts/check_metric_names.py", 0,
             f"coverage self-check: only {sites} registry call sites "
             f"matched (expected >= {MIN_EXPECTED_SITES}) — the "
             "receiver heuristic no longer matches the codebase "
             "idiom"))
    return violations, sites


def main() -> int:
    violations, sites = check()
    if not violations:
        print(f"metric-name convention OK over {sites} registry "
              "call sites")
        return 0
    print("metric-name violations:")
    for rel, line, msg in violations:
        print(f"  {rel}:{line}: {msg}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
