#!/usr/bin/env python
"""Static metric-name convention check (DESIGN-OBSERVABILITY.md
§Metric naming convention).

Thin wrapper: the check lives in
``scripts/analysis/metric_names.py`` on the shared pass framework
(DESIGN-ANALYSIS.md); this CLI and its ``check()`` API are kept for
the historic call sites.  Exit 0 clean; exit 1 with a report.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import core, metric_names  # noqa: E402
from analysis.metric_names import (MIN_EXPECTED_SITES,  # noqa: F401,E402
                                   _check_name)


def check() -> Tuple[List[Tuple[str, int, str]], int]:
    """(violations as (repo-relative path, line, message), sites)."""
    cb = core.Codebase.load()
    violations, sites = metric_names.scan(cb)
    kept = []
    for v in violations:
        sups = cb.suppressions_at(v.rel, v.line, metric_names.NAME)
        if not sups:
            kept.append((v.rel, v.line, v.message))
    return kept, sites


def main() -> int:
    violations, sites = check()
    if not violations:
        print(f"metric-name convention OK over {sites} registry "
              "call sites")
        return 0
    print(metric_names.REPORT_HEADER)
    for rel, line, msg in violations:
        print(f"  {rel}:{line}: {msg}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
