"""GPT-3 1.3B (config 4) compiled memory-fit proof (VERDICT r4 next #2).

AOT-compiles the FULL hybrid train step of `gpt3_1p3b` (hidden 2048,
24 layers, vocab 50304) — dp2 x mp2 x pp2 and mp2 x pp4 over the
virtual 8-CPU mesh at realistic shapes (seq 2048, micro_bs 2,
accumulate 4, stage remat ON = upstream config 4's recompute +
gradient-merge) — and records XLA `CompiledMemoryStats` per device,
asserting the per-chip resident total (arguments + peak temporaries)
fits the 16 GB v5e HBM budget.

CPU-backend layouts: buffer BYTE sizes for the dominant tensors
(f32/bf16 matmul weights, optimizer moments, activation temporaries)
are identical to TPU; TPU layout padding on [8,128] tiles adds <2% for
these shapes (all dims multiples of 256).  The remat *ratio* evidence
is in pp_memory_analysis.py; this script is the absolute budget check
the 1.3B claim needs.

Run:  python scripts/gpt3_memory_fit.py [--arm pp2|pp4|both]
Emits one JSON line per arm and exits nonzero if any arm busts budget.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

V5E_HBM_BYTES = 16 * 2**30
# leave headroom for XLA's reserved/system allocations on a real chip
BUDGET_BYTES = int(V5E_HBM_BYTES * 0.9)


def fit(pp, mp, dp, seq=2048, micro_bs=2, acc=4, seed_params=True):
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
        import PipelineParallel
    from paddle_tpu.models import gpt3_1p3b, GPTForCausalLMPipe
    from paddle_tpu.framework import random as _random

    devices = jax.devices()
    assert pp * mp * dp <= len(devices)
    mesh = collective.build_mesh({"pp": pp, "dp": dp, "mp": mp},
                                 devices=devices[:pp * dp * mp])
    prev = collective.get_mesh()
    collective.set_mesh(mesh)
    try:
        paddle.seed(0)
        cfg = gpt3_1p3b(hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0,
                        max_position_embeddings=seq,
                        use_flash_attention=False)
        t0 = time.time()
        # AOT memory analysis needs shapes, not values: LazyGuard cuts
        # the 1.3B eager random-init (~6 min single-core) to seconds
        with paddle.LazyGuard():
            net = GPTForCausalLMPipe(cfg, num_stages=pp)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=net.parameters())
        n_params = sum(int(np.prod(p.shape)) for p in net.parameters())

        class _Strat:
            pipeline_configs = {"accumulate_steps": acc,
                                "micro_batch_size": micro_bs,
                                "remat_stage": True}

        eng = PipelineParallel(net, None, _Strat())
        eng._plan = eng._build_plan(mesh)
        eng._place(opt)
        step = eng._build_step()

        B = micro_bs * acc * dp
        # the shared schedule body takes the FULL train batch and
        # reshapes into `acc` microbatches in-program (ISSUE 15)
        xs = np.zeros((B, seq), np.int64)
        lr = jnp.asarray(1e-4, jnp.float32)
        key = _random.default_generator().draw_key()
        t1 = time.time()
        lowered = step.lower(eng._params, eng._frozen, eng._buffers,
                             eng._opt_tree, lr, key, xs, xs)
        compiled = lowered.compile()
        t2 = time.time()
        ma = compiled.memory_analysis()
        args_b = int(ma.argument_size_in_bytes)
        temp_b = int(ma.temp_size_in_bytes)
        out_b = int(ma.output_size_in_bytes)
        alias_b = int(getattr(ma, "alias_size_in_bytes", 0))
        # resident set while the step runs on one chip: live inputs
        # (params/opt shards; donated/aliased outputs overlap inputs,
        # so alias bytes are not double-resident) + peak temporaries
        resident = args_b + temp_b + max(out_b - alias_b, 0)
        rec = {
            "arm": f"dp{dp}xmp{mp}xpp{pp}",
            "model": "gpt3_1p3b",
            # CPU lowering uses the composed O(S^2) attention (Pallas
            # flash is TPU-only), so temp is an UPPER bound on the TPU
            # figure: at seq 2048 the [B,H,S,S] probability tensors the
            # flash kernels never materialize dominate the temp pool.
            "note": "temp is an upper bound (composed O(S^2) attention "
                    "on CPU; TPU flash path materializes O(S) instead)",
            "n_params": n_params,
            "seq": seq, "micro_bs": micro_bs, "acc": acc,
            "remat": True,
            "args_gb": round(args_b / 2**30, 3),
            "temp_gb": round(temp_b / 2**30, 3),
            "out_gb": round(out_b / 2**30, 3),
            "alias_gb": round(alias_b / 2**30, 3),
            "resident_gb": round(resident / 2**30, 3),
            "budget_gb": round(BUDGET_BYTES / 2**30, 3),
            "fits_v5e": resident <= BUDGET_BYTES,
            "init_s": round(t1 - t0, 1),
            "compile_s": round(t2 - t1, 1),
        }
        return rec
    finally:
        collective.set_mesh(prev)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arm", default="both",
                    choices=["pp2", "pp4", "both"])
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--micro_bs", type=int, default=2)
    ap.add_argument("--acc", type=int, default=4)
    args = ap.parse_args()
    arms = []
    if args.arm in ("pp2", "both"):
        arms.append((2, 2, 2))
    if args.arm in ("pp4", "both"):
        arms.append((4, 2, 1))
    ok = True
    for pp, mp, dp in arms:
        rec = fit(pp, mp, dp, seq=args.seq, micro_bs=args.micro_bs,
                  acc=args.acc)
        print(json.dumps(rec), flush=True)
        ok = ok and rec["fits_v5e"]
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
