"""8 -> 64 -> 256 chip scaling-efficiency projection (VERDICT r4 #3).

Analytic model, grounded in (a) the round-5 MEASURED single-chip v5e
step times (BASELINE.md) and (b) the HLO collective audit
(tests/test_hlo_collective_audit.py) which verifies the model's two
structural premises on the compiled program: the dp axis carries
exactly the gradient all-reduce (4 bytes x per-chip grad elements at
f32) and every other collective stays on intra-slice (ICI) axes.

Topology: v5e-256 = 8 slices x 32 chips; v5e-64 = 2 x 32; v5e-8 = one
slice (no DCN).  Mesh layout rule (DESIGN-DCN.md): dp outermost, so
slice boundaries cut only dp.

Per-step comm model (weak scaling, per-chip batch fixed):
  ICI  all-reduce: t = 2*(n-1)/n * G_chip / BW_ici
  DCN exchange   : t = 2*(S-1)/S * G_chip / BW_dcn  (hierarchical AR:
                   intra-slice reduce-scatter leaves each chip 1/32 of
                   the slice sum; the inter-slice exchange of those
                   shards is BW_dcn per chip-pipe aggregated per slice)
  overlap        : OVERLAP of the DCN time hides under backward
                   (XLA latency-hiding scheduler; the dp all-reduce is
                   off the critical path until the optimizer update)
  efficiency     = t_compute / (t_compute + t_ici + exposed_dcn)

Compression (compressed.py): bf16 = 2 bytes/elt exact; int8 EQuARX
ring = (8 + 16/256) bits ~ 1.008 bytes/elt + fp32 block scales.

Run: python scripts/scaling_projection.py [--emit-md]
"""

import argparse

# measured round-5 v5e single-chip step times (BASELINE.md)
CONFIGS = [
    # name, step_ms (measured), grad elements per chip replica-group,
    # note
    ("ResNet-50 b64 (config 2, pure dp)", 44.46, 25.6e6, ""),
    ("ERNIE-3.0-base b16 s512 (config 3)", 103.64, 118e6,
     "sharding-2 keeps moments sharded; grads still all-reduce"),
    ("GPT-2-small b8 s1024", 132.0, 124e6, ""),
    ("GPT-3 1.3B mp2xpp2 (config 4)", 4 * 132.0, 1.316e9 / 4,
     "per-chip grads = P/(mp*pp); step est. 4x GPT-small-class"),
]

BW_ICI = 90e9     # effective per-chip all-reduce bandwidth inside a
                  # slice (v5e 2D-torus ring algorithm bandwidth)
BW_DCN = 25e9     # effective per-chip inter-slice exchange bandwidth
                  # (per slice aggregate / 32 chips sharing it)
OVERLAP = 0.7     # DCN fraction hidden under backward
SLICE = 32        # chips per slice

BYTES = {"f32": 4.0, "bf16": 2.0, "int8": 8.0 / 8 + 16.0 / (8 * 256)}


def efficiency(step_ms, grad_elems, n_chips, wire):
    t_c = step_ms / 1e3
    n_ici = min(n_chips, SLICE)
    g_ici = grad_elems * 4.0          # intra-slice AR stays f32
    t_ici = 2 * (n_ici - 1) / n_ici * g_ici / BW_ICI
    n_slices = max(n_chips // SLICE, 1)
    if n_slices > 1:
        g_dcn = grad_elems * BYTES[wire] / SLICE  # post-RS shard/chip
        t_dcn = 2 * (n_slices - 1) / n_slices * g_dcn * SLICE / BW_DCN
        exposed = t_dcn * (1 - OVERLAP)
    else:
        exposed = 0.0
    return t_c / (t_c + t_ici + exposed)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--emit-md", action="store_true")
    args = ap.parse_args()
    rows = []
    for name, ms, g, note in CONFIGS:
        for wire in ("f32", "bf16", "int8"):
            effs = [efficiency(ms, g, n, wire) for n in (8, 64, 256)]
            rows.append((name, wire, effs))
    hdr = ("| config | dp wire | eff@8 | eff@64 | eff@256 |\n"
           "|---|---|---|---|---|")
    print(hdr)
    for name, wire, effs in rows:
        print(f"| {name} | {wire} | " +
              " | ".join(f"{e*100:.1f}%" for e in effs) + " |")
    print()
    print(f"assumptions: BW_ici={BW_ICI/1e9:.0f} GB/s/chip, "
          f"BW_dcn={BW_DCN/1e9:.0f} GB/s/chip-equiv per slice, "
          f"overlap={OVERLAP}, slice={SLICE} chips; "
          "intra-slice AR f32; structure validated by "
          "tests/test_hlo_collective_audit.py")


if __name__ == "__main__":
    main()
