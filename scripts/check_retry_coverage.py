#!/usr/bin/env python
"""Static retry-coverage check (DESIGN-RESILIENCE.md).

Thin wrapper: the check lives in
``scripts/analysis/retry_coverage.py`` on the shared pass framework
(DESIGN-ANALYSIS.md); this CLI and its ``check()`` API are kept for
the historic call sites.  Exit 0 clean; exit 1 with a report.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import core, retry_coverage  # noqa: E402


def check() -> List[Tuple[str, int, str]]:
    """Violations as (path-relative-to-paddle_tpu, line, message)."""
    cb = core.Codebase.load()
    prefix = core.PKG_REL + os.sep
    return [(v.rel[len(prefix):] if v.rel.startswith(prefix) else v.rel,
             v.line, v.message)
            for v in core.run_pass(cb, retry_coverage)]


def main() -> int:
    violations = check()
    if not violations:
        print(retry_coverage.OK_MESSAGE)
        return 0
    print(retry_coverage.REPORT_HEADER)
    for rel, line, msg in violations:
        print(f"  paddle_tpu/{rel}:{line}: {msg}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
