#!/usr/bin/env python
"""Static retry-coverage check (DESIGN-RESILIENCE.md).

Every network and checkpoint-IO call site in ``paddle_tpu/`` must route
through the resilience retry layer — a bare ``urlopen`` or orbax
save/restore call is a latent pod-killer on real infrastructure, where
transient 5xx / NFS stalls are routine.  The rule is enforced
structurally, no CI required: ``tests/test_resilience.py`` runs this
script as a plain test.

Checked invariants:

1. ``urllib.request.urlopen`` (or bare ``urlopen``) may only be called
   inside a function whose enclosing module imports the resilience
   retry layer AND whose function body routes through it
   (``retry_call(...)`` / ``@retryable``) — or in an allowlisted
   module that documents why it is exempt.
2. Orbax manager IO (``self._mgr.save/restore``) in the checkpoint
   manager must likewise sit in retry-routed functions.

Exit 0 clean; exit 1 with a violation report otherwise.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddle_tpu")

# modules where a bare urlopen is acceptable, with the reason on record
URLOPEN_ALLOWLIST = {
    # the retry layer itself obviously sits below retry_call
    os.path.join("distributed", "resilience", "retry.py"),
    # the controller's fleet metrics scrape is best-effort BY DESIGN:
    # a failed member scrape means "absent this round" (counted on
    # fleet_scrape_errors_total), never a judgment, and the next
    # scrape interval retries naturally — blocking the 4 Hz watch
    # loop on urlopen retries would delay the failure detection the
    # loop exists for (DESIGN-OBSERVABILITY.md §Distributed plane)
    os.path.join("distributed", "launch", "controller.py"),
}

CHECKPOINT_MANAGER = os.path.join("distributed", "checkpoint",
                                  "manager.py")


def _is_urlopen(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "urlopen"
    if isinstance(f, ast.Attribute):
        return f.attr == "urlopen"
    return False


def _is_ckpt_io(call: ast.Call) -> bool:
    """self._mgr.save(...) / self._mgr.restore(...) — the raw orbax
    manager IO inside the checkpoint manager."""
    f = call.func
    return (isinstance(f, ast.Attribute)
            and f.attr in ("save", "restore")
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "_mgr")


def _routes_through_retry(func: ast.AST) -> bool:
    """The function either calls retry_call / retry.retry_call or is
    wrapped by @retryable."""
    for deco in getattr(func, "decorator_list", []):
        base = deco.func if isinstance(deco, ast.Call) else deco
        name = base.attr if isinstance(base, ast.Attribute) else \
            getattr(base, "id", "")
        if name == "retryable":
            return True
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                getattr(f, "id", "")
            if name == "retry_call":
                return True
    return False


def _retry_wrapped_names(tree: ast.Module) -> set:
    """Names of functions handed to ``retry_call`` as the callable —
    ``retry_call(self._send, ...)`` / ``retry_call(_write, ...)``:
    their bodies hold the raw IO by design."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else \
            getattr(f, "id", "")
        if fname != "retry_call":
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute):
            names.add(arg.attr)
        elif isinstance(arg, ast.Name):
            names.add(arg.id)
    return names


def _enclosing_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def check() -> List[Tuple[str, int, str]]:
    violations: List[Tuple[str, int, str]] = []
    for dirpath, _, files in os.walk(PKG):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, PKG)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError as e:
                    violations.append((rel, e.lineno or 0,
                                       f"syntax error: {e.msg}"))
                    continue
            # every enclosing function of each interesting call
            # (innermost last), plus the module-wide set of functions
            # that are themselves handed to retry_call
            funcs = list(_enclosing_functions(tree))
            chains = {}
            for fn in funcs:
                for n in ast.walk(fn):
                    chains.setdefault(id(n), []).append(fn)
            wrapped = _retry_wrapped_names(tree)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = None
                if _is_urlopen(node) and rel not in URLOPEN_ALLOWLIST:
                    kind = "urlopen"
                elif rel == CHECKPOINT_MANAGER and _is_ckpt_io(node):
                    kind = "checkpoint-IO"
                if kind is None:
                    continue
                chain = chains.get(id(node), [])
                if not chain:
                    violations.append(
                        (rel, node.lineno,
                         f"module-level {kind} call (unretried)"))
                elif not any(_routes_through_retry(fn)
                             or fn.name in wrapped for fn in chain):
                    violations.append(
                        (rel, node.lineno,
                         f"{kind} call in {chain[-1].name}() does not "
                         "route through resilience.retry "
                         "(retry_call/@retryable)"))
    return violations


def main() -> int:
    violations = check()
    if not violations:
        print("retry coverage OK: all urlopen/checkpoint-IO sites "
              "route through resilience.retry")
        return 0
    print("retry coverage violations:")
    for rel, line, msg in violations:
        print(f"  paddle_tpu/{rel}:{line}: {msg}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
