#!/bin/bash
# Tunnel watcher + hardware measurement queue.
#
# Polls the axon tunnel; when it is up AND a real jax backend init
# succeeds, runs the round-5 hardware queue in order, logging each step.
# Partial results survive outages (tpu_ab appends to AB_RESULTS.jsonl;
# bench.py writes its JSON line to stdout -> log).  Exits when the whole
# queue has completed, or after MAX_HOURS.
set -u
cd "$(dirname "$0")/.."
LOG=hw_queue.log
MAX_HOURS=${MAX_HOURS:-11}
DEADLINE=$(( $(date +%s) + MAX_HOURS*3600 ))

log() { echo "[$(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

probe() {
    curl -sm 5 http://127.0.0.1:8103/ -o /dev/null -w "%{http_code}" 2>/dev/null
    [ $? -eq 0 ] || return 1
    # TCP up -> confirm a backend init + tiny computation completes
    timeout 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((8, 8), jnp.bfloat16)
assert float((x @ x)[0, 0]) == 8.0
print('backend-ok', jax.devices())" >> "$LOG" 2>&1
}

STEP_FILE=.hw_queue_step
step=$(cat "$STEP_FILE" 2>/dev/null || echo 0)

run_step() {  # $1=idx $2=name $3...=cmd
    local idx=$1 name=$2; shift 2
    if [ "$step" -gt "$idx" ]; then return 0; fi
    log "=== step $idx: $name ==="
    "$@" >> "$LOG" 2>&1
    local rc=$?
    log "=== step $idx: $name done rc=$rc ==="
    if [ $rc -eq 0 ]; then
        step=$((idx+1)); echo "$step" > "$STEP_FILE"
    fi
    return $rc
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    if probe; then
        log "tunnel UP — running queue from step $step"
        run_step 0 "profile_gpt decomposition" \
            timeout 3000 python scripts/profile_gpt.py || { sleep 60; continue; }
        run_step 1 "tpu_ab kernel matrix" \
            timeout 5400 python scripts/tpu_ab.py --timeout 480 --also-vit || { sleep 60; continue; }
        run_step 2 "full bench" \
            timeout 1200 python bench.py || { sleep 60; continue; }
        log "QUEUE COMPLETE"
        exit 0
    fi
    sleep 60
done
log "deadline reached with step=$step"
exit 1
