#!/usr/bin/env python
"""Single entry point for the program-stability analysis suite
(DESIGN-ANALYSIS.md).

One file walk, one ``ast.parse`` per module, eight passes::

    python scripts/lint.py                  # run everything
    python scripts/lint.py host-sync env-knobs   # a subset
    python scripts/lint.py --list           # pass catalog
    python scripts/lint.py --write-env-table     # refresh README

Exit 0 clean; exit 1 with a uniform violation report otherwise.
Suppress a finding in place with ``# lint: allow(<pass>): <reason>``
on the flagged line — the reason is mandatory, the pass name must
exist, and a suppression that no longer silences anything is itself
a violation (the full run enforces all three).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analysis import PASSES, core  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="program-stability analysis suite")
    ap.add_argument("passes", nargs="*",
                    help="pass names to run (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="list the pass catalog and exit")
    ap.add_argument("--write-env-table", action="store_true",
                    help="regenerate the README env-knob table from "
                         "the registry and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name, mod in PASSES.items():
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"  {name:18s} {doc}")
        return 0

    if args.write_env_table:
        from analysis.env_knobs_pass import write_env_table
        changed = write_env_table()
        print("README env-knob table "
              + ("rewritten" if changed else "already fresh"))
        return 0

    selected = args.passes or list(PASSES)
    unknown = [p for p in selected if p not in PASSES]
    if unknown:
        print(f"unknown pass(es): {', '.join(unknown)} "
              f"(known: {', '.join(PASSES)})")
        return 2

    cb = core.Codebase.load()
    violations = []
    for name in selected:
        violations.extend(core.run_pass(cb, PASSES[name]))
    # suppression hygiene rides the full run only: a subset run can't
    # judge suppressions for passes it didn't execute
    violations.extend(core.suppression_violations(
        cb, known_passes=set(PASSES), ran_passes=selected))

    if not violations:
        print(f"lint OK: {len(selected)} pass(es) clean over "
              f"{len(cb.modules)} modules")
        return 0
    print(f"lint: {len(violations)} violation(s):")
    print(core.format_report(violations))
    return 1


if __name__ == "__main__":
    sys.exit(main())
