"""TPU perf A/B matrix — run the moment the axon tunnel returns.

Runs the GPT-2-small bench across the kernel-variant matrix, prints a
table + JSON, and names the winning default:

    variants = baseline (packed flash, no fused CE)
             x PADDLE_TPU_FLASH_NO_PACKED=1
             x PADDLE_TPU_FUSED_LMCE=1
             x both

Usage:  python scripts/tpu_ab.py [--timeout 480] [--also-resnet]

Each variant runs bench.py's GPT child in a fresh subprocess (the
backend-init watchdog applies).  Results append to AB_RESULTS.jsonl so
partial progress survives a mid-run tunnel outage.
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VARIANTS = [
    ("baseline", {}),
    ("no_packed", {"PADDLE_TPU_FLASH_NO_PACKED": "1"}),
    ("fused_lmce", {"PADDLE_TPU_FUSED_LMCE": "1"}),
    ("no_packed+fused_lmce", {"PADDLE_TPU_FLASH_NO_PACKED": "1",
                              "PADDLE_TPU_FUSED_LMCE": "1"}),
    # head-dim-64 MXU experiment (VERDICT r4 #9): head-pair forward
    # kernel — batched 64-contraction dots + full-width softmax lanes
    ("headpack2", {"PADDLE_TPU_FLASH_HEADPACK": "2"}),
    ("headpack2+fused_lmce", {"PADDLE_TPU_FLASH_HEADPACK": "2",
                              "PADDLE_TPU_FUSED_LMCE": "1"}),
    # KV-block sweep around the r3 winner (1024)
    ("bk512", {"PADDLE_TPU_FLASH_BK": "512"}),
    ("bk2048", {"PADDLE_TPU_FLASH_BK": "2048"}),
]


def run_variant(name, env_extra, timeout, child="gpt"):
    env = dict(os.environ)
    env.update(env_extra)
    env["_GRAFT_BENCH_CHILD"] = child
    # each cell IS one variant — suppress bench_gpt's own in-process
    # variant sweep (it would nest extra compiles and mislabel
    # combinations under the cell's env)
    env["GRAFT_BENCH_NO_VARIANTS"] = "1"
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py")],
            env=env, cwd=HERE, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"variant": name, "error": f"timeout {timeout}s"}
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT "):
            r = json.loads(line[len("RESULT "):])
            r["variant"] = name
            r["wall_s"] = round(time.time() - t0, 1)
            return r
    return {"variant": name,
            "error": (proc.stdout + proc.stderr)[-800:]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=int, default=480)
    ap.add_argument("--also-resnet", action="store_true")
    ap.add_argument("--also-vit", action="store_true")
    args = ap.parse_args()

    out_path = os.path.join(HERE, "AB_RESULTS.jsonl")
    results = []
    for name, extra in VARIANTS:
        print(f"--- {name} ({extra}) ---", flush=True)
        r = run_variant(name, extra, args.timeout)
        results.append(r)
        with open(out_path, "a") as f:
            f.write(json.dumps(r) + "\n")
        print(json.dumps(r), flush=True)

    extra_children = []
    if args.also_resnet:
        extra_children.append(("resnet50", "resnet"))
    if args.also_vit:
        extra_children.append(("vit_b16_bucketed", "vit"))
    for label, child in extra_children:
        print(f"--- {label} ---", flush=True)
        r = run_variant(label, {}, args.timeout, child=child)
        results.append(r)
        with open(out_path, "a") as f:
            f.write(json.dumps(r) + "\n")
        print(json.dumps(r), flush=True)

    ok = [r for r in results if "tokens_per_sec" in r]
    if ok:
        print(f"\n{'variant':<22} {'tok/s':>10} {'ms/step':>9} "
              f"{'mfu':>7}")
        for r in ok:
            print(f"{r['variant']:<22} {r['tokens_per_sec']:>10.0f} "
                  f"{r.get('step_ms', 0):>9.2f} "
                  f"{r.get('mfu', 0):>7.4f}")
        best = max(ok, key=lambda r: r["tokens_per_sec"])
        print(f"\nWINNER: {best['variant']} "
              f"({best['tokens_per_sec']:.0f} tok/s). Defaults to flip "
              "if not baseline: packed -> ops/pallas_ops.py "
              "_packed_eligible; fused lmce -> bench_gpt/"
              "enable_fused_lmce.")


if __name__ == "__main__":
    main()
