#!/usr/bin/env python
"""Static FaultPlan site-name check (DESIGN-RESILIENCE.md).

Chaos rules target injection sites by *string name*; a typo on either
side produces an injection point that silently never fires — the
recovery path looks chaos-tested while nothing is being injected.
Enforced structurally like ``check_retry_coverage.py`` (run as a
plain test in ``tests/test_resilience.py``, no CI needed):

1. every string-literal site passed to ``fault_point(...)`` /
   ``should_drop(...)`` in production code (``paddle_tpu/``) must
   appear in the central registry
   (``resilience.faults.KNOWN_SITES``);
2. every registry name must be wired into at least one production
   call site (a registry entry with zero call sites is a recovery
   path whose chaos coverage silently evaporated);
3. call sites must use a string literal — a computed site name can't
   be audited and defeats the registry.

Exit 0 clean; exit 1 with a violation report otherwise.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddle_tpu")

_INJECT_FNS = {"fault_point", "should_drop"}


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    return getattr(f, "id", "")


def _iter_sites():
    """Yield (relpath, lineno, site|None) for every injection call in
    the package; site is None when the first arg is not a literal."""
    for dirpath, _, files in os.walk(PKG):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, PKG)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue  # check_retry_coverage reports these
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                if _call_name(node) not in _INJECT_FNS:
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    yield rel, node.lineno, arg.value
                else:
                    yield rel, node.lineno, None


def check() -> List[Tuple[str, int, str]]:
    sys.path.insert(0, REPO)
    try:
        from paddle_tpu.distributed.resilience.faults import KNOWN_SITES
    finally:
        sys.path.pop(0)
    violations: List[Tuple[str, int, str]] = []
    used: Set[str] = set()
    # the registry's own module defines the names, it doesn't call them
    registry_mod = os.path.join("distributed", "resilience", "faults.py")
    for rel, line, site in _iter_sites():
        if rel == registry_mod:
            continue
        if site is None:
            violations.append(
                (rel, line, "injection site is not a string literal "
                 "(unauditable; name sites statically)"))
        elif site not in KNOWN_SITES:
            violations.append(
                (rel, line, f"unknown fault site {site!r} — add it to "
                 "resilience.faults.KNOWN_SITES or fix the typo"))
        else:
            used.add(site)
    for site in sorted(KNOWN_SITES - used):
        violations.append(
            (registry_mod, 0,
             f"registered fault site {site!r} has no production call "
             "site — dead registry entry or a typo'd call"))
    return violations


def main() -> int:
    violations = check()
    if not violations:
        print("fault-site coverage OK: every injection site is "
              "registered and every registered site is wired")
        return 0
    print("fault-site violations:")
    for rel, line, msg in violations:
        print(f"  paddle_tpu/{rel}:{line}: {msg}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
