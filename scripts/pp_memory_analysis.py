"""Pipeline-parallel compiled peak-memory evidence (VERDICT r3 next #9).

AOT-compiles the full hybrid PipelineParallel train step (GPT pipe
model, dp×mp×pp over the virtual 8-CPU mesh) and records XLA's
CompiledMemoryStats with stage remat ON vs OFF, at pp=2 and pp=4.

The absolute numbers are CPU-backend layouts, but the remat ratio and
its pp-scaling are the quantity of interest: they substantiate the
module-header claim that GPipe-with-remat recovers 1F1B's activation-
memory advantage (pipeline_parallel.py:14-21).  Run:

    python scripts/pp_memory_analysis.py [--hidden 512 --layers 8]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def analyze(pp, remat, hidden, layers, seq, micro_bs, acc):
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
        import PipelineParallel
    from paddle_tpu.models import GPTConfig, GPTForCausalLMPipe
    from paddle_tpu.framework import random as _random

    devices = jax.devices()
    mp = 1
    dp = len(devices) // (pp * mp)
    mesh = collective.build_mesh({"pp": pp, "dp": dp, "mp": mp},
                                 devices=devices[:pp * dp * mp])
    prev = collective.get_mesh()
    collective.set_mesh(mesh)
    try:
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=8192, hidden_size=hidden,
                        num_hidden_layers=layers,
                        num_attention_heads=max(hidden // 64, 1),
                        intermediate_size=4 * hidden,
                        max_position_embeddings=seq,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0,
                        use_flash_attention=False)
        net = GPTForCausalLMPipe(cfg, num_stages=pp)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=net.parameters())

        class _Strat:
            pipeline_configs = {"accumulate_steps": acc,
                                "micro_batch_size": micro_bs,
                                "remat_stage": remat}

        eng = PipelineParallel(net, None, _Strat())
        eng._plan = eng._build_plan(mesh)
        eng._place(opt)
        step = eng._build_step()

        B = micro_bs * acc * dp
        # the shared schedule body takes the FULL train batch and
        # reshapes into `acc` microbatches in-program (ISSUE 15)
        xs = np.zeros((B, seq), np.int64)
        lr = jnp.asarray(1e-3, jnp.float32)
        key = _random.default_generator().draw_key()
        lowered = step.lower(eng._params, eng._frozen, eng._buffers,
                             eng._opt_tree, lr, key, xs, xs)
        ma = lowered.compile().memory_analysis()
        return {
            "temp_mb": ma.temp_size_in_bytes / 2**20,
            "args_mb": ma.argument_size_in_bytes / 2**20,
            "out_mb": ma.output_size_in_bytes / 2**20,
        }
    finally:
        collective.set_mesh(prev)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--micro_bs", type=int, default=2)
    ap.add_argument("--acc", type=int, default=4)
    args = ap.parse_args()

    print(f"# GPT pipe hidden={args.hidden} layers={args.layers} "
          f"seq={args.seq} micro_bs={args.micro_bs} "
          f"acc={args.acc} (8 virtual CPU devices)")
    print(f"{'pp':>3} {'remat':>6} {'temp_MB':>10} {'args_MB':>10} "
          f"{'ratio':>7}")
    for pp in (2, 4):
        base = None
        for remat in (False, True):
            r = analyze(pp, remat, args.hidden, args.layers, args.seq,
                        args.micro_bs, args.acc)
            if not remat:
                base = r["temp_mb"]
            ratio = r["temp_mb"] / base if base else 1.0
            print(f"{pp:>3} {str(remat):>6} {r['temp_mb']:>10.1f} "
                  f"{r['args_mb']:>10.1f} {ratio:>7.2f}")


if __name__ == "__main__":
    main()
