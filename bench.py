"""Benchmark: GPT-2-small causal-LM training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workload: the ERNIE/GPT class of baseline configs (BASELINE.json:9-10)
reduced to one chip — bf16 train step (fwd+bwd+AdamW) of a 124M-param
GPT-2-small at batch 8 × seq 1024, compiled to a single XLA program.
A ResNet-50 images/s figure (BASELINE.json:8) is reported as an extra
field when time allows.

vs_baseline: BASELINE.md records no published reference numbers
("published": {} — empty reference mount), so the denominator is the
community-typical per-A100 figure for GPT-2-small-class training used
as the provisional bar: 25k tokens/s/GPU.  Replace when real reference
numbers exist.

Robustness (round-1 failure mode, VERDICT.md weak #2): the TPU backend
can fail or hang during init (`jax.devices()` never returns).  The
parent process therefore runs each workload in a child with a
backend-init watchdog and an overall deadline, retries once when the
failure was early (init-class), and always emits a parseable JSON line.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

BASELINE_TOKENS_PER_SEC = 25_000.0
BASELINE_RESNET50_IMG_PER_SEC = 400.0   # community per-A100 fp16 figure

INIT_DEADLINE_S = 150     # child must report `devices-ok` within this
GPT_DEADLINE_S = 480      # full GPT bench wall-clock cap
GLOBAL_DEADLINE_S = 900   # parent never runs longer than this
RETRY_ONLY_BEFORE_S = 240  # retry only if attempt 1 failed early


AXON_HOST, AXON_PORT = "127.0.0.1", 8103


def _emit_result(mode: str, out: dict):
    """Print the child's RESULT record with the process-wide
    observability snapshot attached (ROADMAP observability follow-up):
    instead of each workload hand-rolling its own stats dict, the full
    metrics registry + trace summary land in one
    ``observability.export.dump_json`` file per child, and the RESULT
    record carries its path — so a bench round's record can answer
    anything the registry can (dispatch counts, checkpoint IO,
    serving histograms), not just the headline numbers."""
    try:
        from paddle_tpu.observability import export as _obs_export
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".bench_obs", f"{mode}.json")
        out[f"obs_snapshot_{mode}"] = _obs_export.dump_json(path)
    except Exception as e:  # a metrics failure must not eat the result
        out[f"obs_snapshot_{mode}_error"] = f"{type(e).__name__}: {e}"
    print("RESULT " + json.dumps(out), flush=True)


def _probe_axon(timeout=5.0):
    """Pre-flight TCP probe of the axon TPU tunnel (VERDICT r4 weak #2):
    a 0.0 bench record must distinguish tunnel-outage from code
    regression.  Returns True iff something accepts on the tunnel port."""
    try:
        with socket.create_connection((AXON_HOST, AXON_PORT),
                                      timeout=timeout):
            return True
    except OSError:
        return False


def _maybe_force_cpu():
    # Testing hook: exercise the bench mechanics without TPU hardware.
    # Must run before any backend init; the axon plugin ignores the
    # JAX_PLATFORMS env var, so use the config switch.
    if os.environ.get("GRAFT_BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")


def _timed_bench(build, steps, pipeline_steps=0, batch_gen=None,
                 runner_kwargs=None, timings=None):
    """Shared scaffold: build (model, opt, loss, data) then time steps.

    `build` returns (net, opt, loss_fn, inputs, labels, units_per_step).
    Returns (units/sec, step_ms[, pipeline_units/sec]) over `steps`
    timed steps after compile + warmup.  The base measurement stages
    inputs once; when `batch_gen` is given, a second loop feeds FRESH
    host batches through the DataLoader's device double-buffer
    (_DevicePrefetcher) so the number includes real input-pipeline
    overlap (VERDICT r3 next #8).  ``timings`` (optional dict) receives
    ``train_compile_s`` — model build + placement + first compiled
    step, the per-process cold-start cost the training rounds record
    every round like serving records ``serving_compile_warmup_s``
    (ROADMAP "compile-time as a product metric")."""
    _maybe_force_cpu()
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.runner import DistributedRunner

    print("devices-ok", jax.devices(), flush=True)
    t_build0 = time.perf_counter()
    paddle.seed(0)
    net, opt, loss_fn, inputs, labels, units = build()
    mesh = collective.build_mesh({})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt, loss_fn, mesh=mesh,
                               **(runner_kwargs or {}))
    inputs = [Tensor(jax.device_put(v)) for v in inputs]
    labels = [Tensor(jax.device_put(v)) for v in labels]

    float(runner.train_step(inputs, labels))   # compile
    if timings is not None:
        timings["train_compile_s"] = round(
            time.perf_counter() - t_build0, 2)
    print("compiled", flush=True)
    float(runner.train_step(inputs, labels))   # warmup

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = runner.train_step(inputs, labels)
    jax.block_until_ready(runner._opt_state)
    float(loss)
    dt = time.perf_counter() - t0
    if not (pipeline_steps and batch_gen):
        return units * steps / dt, dt / steps * 1000.0

    # input-pipeline overlap: fresh batches, host gen + H2D double
    # buffered ahead of the consuming step
    from paddle_tpu.io.dataloader import _DevicePrefetcher

    def gen():
        for i in range(pipeline_steps):
            xs, ys = batch_gen(i)
            yield ([Tensor(v) for v in xs], [Tensor(v) for v in ys])

    it = _DevicePrefetcher(gen(), depth=2)
    first = next(it)
    runner.train_step(*first)   # same shapes — no recompile
    jax.block_until_ready(runner._opt_state)   # sync before timing
    t0 = time.perf_counter()
    n = 0
    for batch_in, batch_lb in it:
        loss = runner.train_step(batch_in, batch_lb)
        n += 1
    jax.block_until_ready(runner._opt_state)
    float(loss)
    dt2 = time.perf_counter() - t0
    return (units * steps / dt, dt / steps * 1000.0,
            units * n / dt2 if n else 0.0)


def bench_gpt():
    import numpy as np
    from paddle_tpu import amp, optimizer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))  # mechanics smoke

    def build():
        if tiny:
            cfg = GPTConfig(vocab_size=1024, hidden_size=64,
                            num_hidden_layers=2, num_attention_heads=4,
                            intermediate_size=128,
                            max_position_embeddings=128,
                            hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0,
                            use_flash_attention=False)
            batch, seq = 2, 64
        else:
            cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                            num_hidden_layers=12, num_attention_heads=12,
                            intermediate_size=3072,
                            max_position_embeddings=1024,
                            hidden_dropout_prob=0.0,
                            attention_probs_dropout_prob=0.0,
                            use_flash_attention=True)
            batch, seq = 8, 1024
        net = GPTForCausalLM(cfg)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=net.parameters(),
                              multi_precision=True)
        # O2: bf16 params + fp32 master weights in the optimizer
        amp.decorate(net, opt, level="O2", dtype="bfloat16")
        crit = GPTPretrainingCriterion()
        from paddle_tpu.framework import env_knobs
        if env_knobs.get_raw("PADDLE_TPU_FUSED_LMCE"):
            # A/B knob: fold the lm-head matmul into the Pallas
            # streaming-CE kernel (logits never hit HBM); enable by
            # default once hardware numbers confirm the win
            from paddle_tpu.models import enable_fused_lmce
            enable_fused_lmce(net, crit)
        rng = np.random.RandomState(0)
        x = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        y = np.roll(x, -1, axis=1)
        return (net, opt, crit, [x], [y], batch * seq)

    def batch_gen(i):
        rng = np.random.RandomState(1000 + i)
        vocab = 1024 if tiny else 50304
        b, s = (2, 64) if tiny else (8, 1024)
        x = rng.randint(0, vocab, (b, s)).astype(np.int64)
        return [x], [np.roll(x, -1, axis=1)]

    t_child0 = time.time()
    timings = {}
    res = _timed_bench(build, steps=2 if tiny else 15,
                       pipeline_steps=3 if tiny else 10,
                       batch_gen=batch_gen, timings=timings)
    tps, step_ms = res[0], res[1]
    tps_pipe = res[2] if len(res) > 2 else None

    # In-process kernel-variant A/B (VERDICT r3 next #1/#2): the
    # packed-heads flash layout ships default-ON but was never
    # perf-measured on hardware, and the fused lm-head CE kernel is
    # new.  Measure both as extra fields so the driver's round-end
    # bench captures the comparison even without interactive TPU
    # access.  Each variant costs one fresh compile; skip when the
    # main run already burned most of the child budget.
    variants = {}
    if not os.environ.get("GRAFT_BENCH_NO_VARIANTS"):
        plan = [("fused_lmce", {"PADDLE_TPU_FUSED_LMCE": "1"})]
        if not tiny:
            plan = [("nopacked",
                     {"PADDLE_TPU_FLASH_NO_PACKED": "1"})] + plan
        for vname, venv in plan:
            if time.time() - t_child0 > (60 if tiny else 240):
                variants[vname] = "skipped: out of child budget"
                continue   # mark EVERY remaining variant, don't vanish
            saved = {k: os.environ.get(k) for k in venv}
            os.environ.update(venv)
            try:
                vres = _timed_bench(build, steps=2 if tiny else 8)
                variants[vname] = round(vres[0], 1)
            except Exception as e:   # variant failure must not kill
                variants[vname] = f"error: {e}"[:300]
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
    # model flops per token (matmul-only, PaLM-style accounting):
    # 6*N for the dense/embedding matmuls + 6*L*d*S for causal
    # attention (12*L*d*S non-causal halved)
    if tiny:
        n_params, L, d, S = 0, 0, 0, 0
        flops_tok = 0.0
    else:
        n_params = 124_439_808          # GPT-2-small incl. tied embed
        L, d, S = 12, 768, 1024
        flops_tok = 6.0 * n_params + 6.0 * L * d * S
    out = {"tokens_per_sec": tps, "step_ms": round(step_ms, 2)}
    out.update(timings)        # train_compile_s: cold-start on record
    if tps_pipe:
        out["tokens_per_sec_pipeline"] = round(tps_pipe, 1)
        out["pipeline_overlap_ratio"] = round(tps_pipe / tps, 3)
    for vname, v in variants.items():
        out[f"tokens_per_sec_{vname}"] = v
    if flops_tok:
        peak = float(os.environ.get("GRAFT_TPU_PEAK_TFLOPS", "197"))
        out["model_tflops_per_sec"] = round(tps * flops_tok / 1e12, 2)
        out["mfu"] = round(tps * flops_tok / (peak * 1e12), 4)
        out["flops_per_token_m"] = round(flops_tok / 1e6, 1)
    _emit_result("gpt", out)


def bench_resnet():
    import numpy as np
    from paddle_tpu import amp, nn, optimizer
    from paddle_tpu.vision import models as vmodels

    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))  # mechanics smoke
    batch, size, classes = (4, 32, 10) if tiny else (64, 224, 1000)

    def build():
        net = vmodels.resnet18(num_classes=classes) if tiny \
            else vmodels.resnet50()
        opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                 parameters=net.parameters(),
                                 multi_precision=True)
        amp.decorate(net, opt, level="O2", dtype="bfloat16")
        rng = np.random.RandomState(0)
        x = rng.rand(batch, 3, size, size).astype(np.float32)
        y = rng.randint(0, classes, (batch,)).astype(np.int64)
        return (net, opt, nn.CrossEntropyLoss(), [x], [y], batch)

    # conv needs the auto_cast hook under O2: BN outputs stay fp32,
    # the hook casts conv inputs back to bf16 (upstream O2 forward
    # runs inside auto_cast)
    ips, step_ms = _timed_bench(
        build, steps=2 if tiny else 10,
        runner_kwargs={"amp_level": "O2", "amp_dtype": "bfloat16"})
    # ResNet-50 fwd flops ~4.1 GFLOP/image at 224x224; train ~3x
    flops_img = 3.0 * 4.1e9
    peak = float(os.environ.get("GRAFT_TPU_PEAK_TFLOPS", "197"))
    _emit_result("resnet", {
        "images_per_sec": ips, "step_ms": round(step_ms, 2),
        "mfu": round(ips * flops_img / (peak * 1e12), 4)})


def bench_ernie():
    """ERNIE-3.0-base-class MLM pretrain throughput (the second half of
    the north-star primary metric, BASELINE.json:2)."""
    import numpy as np
    from paddle_tpu import amp, optimizer
    from paddle_tpu.models import (BertForPretraining,
                                   BertPretrainingCriterion, ernie_3_base)

    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))
    if tiny:
        from paddle_tpu.models import BertConfig
        cfg = BertConfig(vocab_size=1024, hidden_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=128,
                         max_position_embeddings=128,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        batch, seq = 2, 64
    else:
        cfg = ernie_3_base(hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
        batch, seq = 16, 512

    def build():
        net = BertForPretraining(cfg)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=net.parameters(),
                              multi_precision=True)
        amp.decorate(net, opt, level="O2", dtype="bfloat16")
        rng = np.random.RandomState(0)
        x = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        # 15% MLM positions; the rest ignore_index=-100
        labels = np.where(rng.rand(batch, seq) < 0.15, x, -100)
        return (net, opt, BertPretrainingCriterion(cfg.vocab_size),
                [x], [labels.astype(np.int64)], batch * seq)

    timings = {}
    tps, step_ms = _timed_bench(build, steps=2 if tiny else 10,
                                timings=timings)
    _emit_result("ernie", {
        "tokens_per_sec": tps, "step_ms": round(step_ms, 2),
        **timings})


def bench_detector():
    """PP-YOLOE-s-class detector train throughput on BUCKETED dynamic
    shapes (config 5's detector half, BASELINE.json:11): one compiled
    program per image-size bucket, alternating buckets per step —
    exactly the dynamic-shape story the upstream detector stresses."""
    import numpy as np
    import jax
    from paddle_tpu import optimizer
    from paddle_tpu.nn import functional_call as F
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.vision.models.ppyoloe import (ppyoloe_crn_s,
                                                  ppyoloe_tiny)
    import paddle_tpu as paddle

    _maybe_force_cpu()
    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))
    paddle.seed(0)
    if tiny:
        net, batch, sizes, steps = ppyoloe_tiny(num_classes=4), 2, \
            (64,), 2
    else:
        net, batch, sizes, steps = ppyoloe_crn_s(num_classes=80), 8, \
            (640, 512), 10
    net.train()
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=net.parameters())
    params = F.param_dict(net)
    frozen = F.frozen_dict(net)
    buffers = F.buffer_dict(net)
    state = opt.init_state_tree(params)

    @jax.jit
    def step(p, st, imgs, boxes, labels, mask):
        def loss_fn(pp):
            with F.bind(net, pp, buffers, frozen):
                out = net(Tensor(imgs), gt_boxes=Tensor(boxes),
                          gt_labels=Tensor(labels), gt_mask=Tensor(mask))
            return out["loss"]._value
        loss, grads = jax.value_and_grad(loss_fn)(p)
        new_p, new_s = opt.apply_gradients_tree(p, grads, st, 1e-3)
        return loss, new_p, new_s

    rng = np.random.RandomState(0)
    gmax = 8

    def batch_for(size):
        imgs = rng.rand(batch, 3, size, size).astype(np.float32)
        boxes = rng.rand(batch, gmax, 4).astype(np.float32) * size
        boxes = np.concatenate([np.minimum(boxes[..., :2],
                                           boxes[..., 2:]),
                                np.maximum(boxes[..., :2],
                                           boxes[..., 2:]) + 4], -1)
        labels = rng.randint(0, 4, (batch, gmax)).astype(np.int64)
        mask = (rng.rand(batch, gmax) < 0.5).astype(np.float32)
        mask[:, 0] = 1.0
        return imgs, boxes, labels, mask

    data = {s: batch_for(s) for s in sizes}
    for s in sizes:                       # compile each bucket
        loss, params, state = step(params, state, *data[s])
    float(loss)
    t0 = time.perf_counter()
    n = 0
    for i in range(steps):
        s = sizes[i % len(sizes)]
        loss, params, state = step(params, state, *data[s])
        n += batch
    float(loss)
    dt = time.perf_counter() - t0
    _emit_result("detector", {
        "images_per_sec": n / dt,
        "step_ms": round(dt / steps * 1000.0, 2),
        "buckets": list(sizes)})


def bench_vit():
    """ViT-B/16 train throughput on BUCKETED multi-resolution input
    (config 5's ViT half, BASELINE.json:11): position embeddings
    interpolate per bucket, one compiled program per bucket,
    alternating buckets per step."""
    import numpy as np
    import jax
    from paddle_tpu import optimizer, nn
    from paddle_tpu.nn import functional_call as F
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.vision.models import VisionTransformer
    import paddle_tpu as paddle

    _maybe_force_cpu()
    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))
    paddle.seed(0)
    if tiny:
        net = VisionTransformer(img_size=32, patch_size=8, in_chans=3,
                                num_classes=4, embed_dim=64, depth=2,
                                num_heads=4)
        batch, sizes, steps = 2, (32, 48), 2   # 48 exercises pos-embed
        # interpolation even in the tiny smoke
    else:
        net = VisionTransformer(img_size=224, patch_size=16,
                                num_classes=1000)   # ViT-B/16
        batch, sizes, steps = 32, (224, 192), 10
    net.train()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=net.parameters(),
                          multi_precision=True)
    from paddle_tpu import amp
    amp.decorate(net, opt, level="O2", dtype="bfloat16")
    lossf = nn.CrossEntropyLoss()
    params = F.param_dict(net)
    frozen = F.frozen_dict(net)
    buffers = F.buffer_dict(net)
    state = opt.init_state_tree(params)

    @jax.jit
    def step(p, st, imgs, labels):
        def loss_fn(pp):
            # O2 forward runs inside auto_cast (upstream contract; the
            # hook casts f32 inputs to the bf16 params' dtype)
            from paddle_tpu.amp import auto_cast
            with F.bind(net, pp, buffers, frozen):
                with auto_cast(level="O2", dtype="bfloat16"):
                    out = net(Tensor(imgs))
                return lossf(out, Tensor(labels))._value
        loss, grads = jax.value_and_grad(loss_fn)(p)
        new_p, new_s = opt.apply_gradients_tree(p, grads, st, 1e-3)
        return loss, new_p, new_s

    rng = np.random.RandomState(0)
    data = {}
    for s in sizes:
        imgs = rng.rand(batch, 3, s, s).astype(np.float32)
        labels = rng.randint(0, 4 if tiny else 1000,
                             (batch,)).astype(np.int64)
        data[s] = (imgs, labels)
    for s in sizes:                       # compile each bucket
        loss, params, state = step(params, state, *data[s])
    float(loss)
    t0 = time.perf_counter()
    n = 0
    for i in range(steps):
        s = sizes[i % len(sizes)]
        loss, params, state = step(params, state, *data[s])
        n += batch
    float(loss)
    dt = time.perf_counter() - t0
    _emit_result("vit", {
        "images_per_sec": n / dt,
        "step_ms": round(dt / steps * 1000.0, 2),
        "buckets": list(sizes)})


def bench_hapi():
    """Model.fit loop-overhead microbench — CPU by DESIGN, so the
    number stays comparable while the axon TPU tunnel is down
    (BENCH_r05: backend init timeout).  A deliberately tiny fixed-shape
    MLP makes the compiled step ~free; steps/s then tracks the HOST
    side of the hot loop: dispatch, train-state plumbing, metric and
    logging syncs (DESIGN-PERF.md).

    Fold sweep (ISSUE 5): GRAFT_BENCH_HAPI_FOLDS (default "1,8") lists
    the ``steps_per_dispatch`` values to measure.  All folds run
    back-to-back inside ONE child, interleaved rep by rep, so the
    medians-of-3 stay comparable on this noisy shared container.
    Fold 1 doubles as the no-regression guard against the PR-4 loop."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    print("devices-ok", jax.devices(), flush=True)
    folds = [int(f) for f in os.environ.get(
        "GRAFT_BENCH_HAPI_FOLDS", "1,8").split(",")]
    reps = int(os.environ.get("GRAFT_BENCH_HAPI_REPS", "3"))
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                        nn.Linear(32, 10))
    model = paddle.Model(net)
    model.prepare(optimizer.Adam(1e-3, parameters=model.parameters()),
                  nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    rng = np.random.RandomState(0)
    batches = [[rng.rand(16, 16).astype(np.float32),
                rng.randint(0, 10, (16,)).astype(np.int64)]
               for _ in range(48)]
    steps = len(batches)
    epochs = 8
    t_compile0 = time.perf_counter()
    for f in folds:   # compile + warmup epoch per fold entry
        model.fit(batches, epochs=1, verbose=0, steps_per_dispatch=f)
    # cold-start on record every round, like serving_compile_warmup_s
    # (ROADMAP "compile-time as a product metric"): first-epoch wall
    # time across the fold sweep = trace + compile + warmup
    hapi_compile_warmup_s = round(time.perf_counter() - t_compile0, 2)
    # tracing overhead (ISSUE 8 acceptance: < 2% on this microbench):
    # the LARGEST fold also runs with the observability span recorder
    # armed, INTERLEAVED with the untraced reps so the paired medians
    # see the same container noise/drift
    from paddle_tpu.observability import trace as _obs_trace
    ftr = max(folds)
    samples = {f: [] for f in folds}
    traced = []
    n_trace_events = 0
    for _ in range(reps):
        for f in folds:   # interleaved: back-to-back medians
            t0 = time.perf_counter()
            model.fit(batches, epochs=epochs, verbose=0,
                      steps_per_dispatch=f)
            jax.block_until_ready(
                [p._value for p in model.network.parameters()])
            dt = time.perf_counter() - t0
            samples[f].append(steps * epochs / dt)
        _obs_trace.clear()
        _obs_trace.enable()
        try:
            t0 = time.perf_counter()
            model.fit(batches, epochs=epochs, verbose=0,
                      steps_per_dispatch=ftr)
            jax.block_until_ready(
                [p._value for p in model.network.parameters()])
            traced.append(steps * epochs / (time.perf_counter() - t0))
        finally:
            _obs_trace.disable()
        n_trace_events = len(_obs_trace.events())
        _obs_trace.clear()
    out = {"hapi_compile_warmup_s": hapi_compile_warmup_s}
    for f in folds:
        med = sorted(samples[f])[len(samples[f]) // 2]
        key = ("hapi_fit_steps_per_sec" if f == 1
               else f"hapi_fit_steps_per_sec_fold{f}")
        out[key] = round(med, 1)
        if f == 1:
            out["hapi_fit_step_ms"] = round(1000.0 / med, 3)
    if 1 in folds:
        base = out["hapi_fit_steps_per_sec"]
        for f in folds:
            if f != 1 and base:
                out[f"hapi_fold{f}_speedup"] = round(
                    out[f"hapi_fit_steps_per_sec_fold{f}"] / base, 3)
    med_tr = sorted(traced)[len(traced) // 2]
    key_off = ("hapi_fit_steps_per_sec" if ftr == 1
               else f"hapi_fit_steps_per_sec_fold{ftr}")
    out[f"hapi_fit_steps_per_sec_fold{ftr}_traced"] = round(med_tr, 1)
    out["hapi_trace_overhead_pct"] = round(
        100.0 * (1.0 - med_tr / out[key_off]), 2)
    out["hapi_trace_events"] = n_trace_events
    # auto-K (ISSUE 7): unasked, the tuner must land K>1 on this
    # host-bound microbench; record the decision alongside the sweep
    model.fit(batches, epochs=2, verbose=0)
    if model._fold_tuner is not None and model._fold_tuner.decided:
        out["hapi_auto_fold"] = model._fold
        d = model._fold_tuner.decision
        out["hapi_auto_host_ms_per_step"] = d["host_ms_per_step"]
        out["hapi_auto_device_ms_per_step"] = d["device_ms_per_step"]
    _emit_result("hapi", out)


def bench_mesh_fold():
    """DistributedRunner fold sweep on a CPU dp mesh (ISSUE 7): the
    mesh half of the unified dispatch engine, measured the same way
    bench_hapi measures the single-chip half.  CPU by DESIGN — 8 fake
    host devices stand in for a multichip slice; what folding removes
    is HOST dispatch overhead, which this measures directly.

    fold=1 dispatches scan-of-1 through the unified engine; fold=K
    dispatches scan-of-K; ``legacy`` is the pre-unification per-step
    ``train_step`` entry, the no-regression guard.  All variants run
    interleaved rep by rep in ONE child for comparable medians."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.runner import DistributedRunner

    print("devices-ok", jax.devices(), flush=True)
    folds = [int(f) for f in os.environ.get(
        "GRAFT_BENCH_MESH_FOLDS", "1,8").split(",")]
    reps = int(os.environ.get("GRAFT_BENCH_MESH_REPS", "3"))
    dp = int(os.environ.get("GRAFT_BENCH_MESH_DP", "2"))
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                        nn.Linear(32, 10))
    opt = optimizer.Adam(1e-3, parameters=net.parameters())
    mesh = collective.build_mesh({"dp": dp})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                               mesh=mesh)
    rng = np.random.RandomState(0)
    batches = [([rng.rand(16, 16).astype(np.float32)],
                [rng.randint(0, 10, (16,)).astype(np.int64)])
               for _ in range(48)]
    steps, rounds = len(batches), 4

    def run_epoch(f):
        if f == 0:                       # legacy per-step entry
            for ins, lbs in batches:
                runner.train_step(ins, lbs)
            return
        for i in range(0, steps, f):
            runner.train_steps_folded(batches[i:i + f])

    variants = [0] + folds               # 0 = legacy baseline
    t_compile0 = time.perf_counter()
    for f in variants:                   # compile + warmup epoch each
        run_epoch(f)
    mesh_compile_warmup_s = round(time.perf_counter() - t_compile0, 2)
    samples = {f: [] for f in variants}
    for _ in range(reps):
        for f in variants:               # interleaved medians
            t0 = time.perf_counter()
            for _ in range(rounds):
                run_epoch(f)
            jax.block_until_ready(runner._opt_state)
            dt = time.perf_counter() - t0
            samples[f].append(steps * rounds / dt)
    out = {"mesh_dp": dp,
           "mesh_compile_warmup_s": mesh_compile_warmup_s}
    for f in variants:
        med = sorted(samples[f])[len(samples[f]) // 2]
        key = ("mesh_fit_steps_per_sec_legacy" if f == 0 else
               "mesh_fit_steps_per_sec" if f == 1 else
               f"mesh_fit_steps_per_sec_fold{f}")
        out[key] = round(med, 1)
    base = out.get("mesh_fit_steps_per_sec")
    for f in folds:
        if f != 1 and base:
            out[f"mesh_fold{f}_speedup"] = round(
                out[f"mesh_fit_steps_per_sec_fold{f}"] / base, 3)
    _emit_result("mesh_fold", out)


def bench_pp_fold():
    """Pipeline-engine fold sweep on a CPU pp=2 mesh (ISSUE 15): the
    pipeline half of the unified dispatch engine, measured like
    --mesh-fold measures the dp half.  CPU by DESIGN — what folding
    removes is HOST work per train batch, which this measures
    directly.

    ``legacy`` is the pre-unification per-batch entry (host-drawn key,
    per-batch stacked-leaf wrapper commit); fold=1 dispatches the
    whole stages×microbatches schedule as scan-of-1 through the
    unified engine; fold=K covers K whole batches per dispatch with
    the wrapper sync deferred to the epoch boundary.  Host-dispatch
    accounting per batch rides the engine's own registry counters
    (``pp_dispatches_total`` = compiled dispatches,
    ``pp_commit_ops_total`` = stacked-leaf wrapper slice ops): the
    ISSUE 15 acceptance — O(1) compiled dispatches per batch at
    fold=1, O(1/K) at fold K — is read straight off the record."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel \
        import PipelineParallel
    from paddle_tpu.framework.dispatch import (AutoFoldTuner,
                                               GroupDispatcher)
    from paddle_tpu.observability import metrics as obs_metrics

    print("devices-ok", jax.devices(), flush=True)
    folds = [int(f) for f in os.environ.get(
        "GRAFT_BENCH_PP_FOLDS", "1,8").split(",")]
    reps = int(os.environ.get("GRAFT_BENCH_PP_REPS", "3"))
    micro = int(os.environ.get("GRAFT_BENCH_PP_MICRO", "4"))

    class Block(nn.Layer):
        def __init__(self, d):
            super().__init__()
            self.fc = nn.Linear(d, d)

        def forward(self, x):
            return nn.functional.relu(self.fc(x))

    paddle.seed(0)
    net = PipelineLayer(
        [nn.Linear(16, 32)] + [Block(32) for _ in range(4)] +
        [nn.Linear(32, 10)],
        num_stages=2, loss_fn=nn.CrossEntropyLoss())
    opt = optimizer.Adam(1e-3, parameters=net.parameters())
    mesh = collective.build_mesh({"pp": 2},
                                 devices=jax.devices()[:2])
    collective.set_mesh(mesh)

    class _Strat:
        pipeline_configs = {"accumulate_steps": micro}

    eng = PipelineParallel(net, None, _Strat(), optimizer=opt)
    rng = np.random.RandomState(0)
    batches = [([rng.rand(16, 16).astype(np.float32)],
                [rng.randint(0, 10, (16,)).astype(np.int64)])
               for _ in range(48)]
    steps, rounds = len(batches), 4
    reg = obs_metrics.registry()

    def counters():
        return {name: reg.counter(name).collect()
                for name in ("pp_dispatches_total",
                             "pp_commit_ops_total")}

    def run_epoch(f):
        if f == 0:                       # legacy per-batch entry
            eng.dispatch_mode = "legacy"
            try:
                for ins, lbs in batches:
                    eng.train_batch((ins[0], lbs[0]), opt)
            finally:
                eng.dispatch_mode = "unified"
            return
        # unified fold path, wrapper sync deferred to the epoch
        # boundary exactly like Model.fit defers it
        eng._defer_wrapper_sync = True
        try:
            for i in range(0, steps, f):
                eng.train_steps_folded(batches[i:i + f])
        finally:
            eng._defer_wrapper_sync = False
            eng.sync_to_layers()

    variants = [0] + folds               # 0 = legacy baseline
    t_compile0 = time.perf_counter()
    for f in variants:                   # compile + warmup epoch each
        run_epoch(f)
    pp_compile_warmup_s = round(time.perf_counter() - t_compile0, 2)
    samples = {f: [] for f in variants}
    dispatch_rec = {}
    for r in range(reps):
        for f in variants:               # interleaved medians
            c0 = counters()
            t0 = time.perf_counter()
            for _ in range(rounds):
                run_epoch(f)
            jax.block_until_ready(eng._opt_tree)
            dt = time.perf_counter() - t0
            samples[f].append(steps * rounds / dt)
            if r == 0:
                c1 = counters()
                n = steps * rounds
                dispatch_rec[f] = {
                    "dispatches_per_batch": round(
                        (c1["pp_dispatches_total"]
                         - c0["pp_dispatches_total"]) / n, 4),
                    "commit_ops_per_batch": round(
                        (c1["pp_commit_ops_total"]
                         - c0["pp_commit_ops_total"]) / n, 4),
                }
    out = {"pp_degree": 2, "pp_microbatches": micro,
           "pp_compile_warmup_s": pp_compile_warmup_s}
    for f in variants:
        med = sorted(samples[f])[len(samples[f]) // 2]
        key = ("pp_fit_steps_per_sec_legacy" if f == 0 else
               "pp_fit_steps_per_sec" if f == 1 else
               f"pp_fit_steps_per_sec_fold{f}")
        out[key] = round(med, 1)
        tag = ("legacy" if f == 0 else
               "fold1" if f == 1 else f"fold{f}")
        for k, v in dispatch_rec.get(f, {}).items():
            out[f"pp_{k}_{tag}"] = v
    base = out.get("pp_fit_steps_per_sec")
    for f in folds:
        if f != 1 and base:
            out[f"pp_fold{f}_speedup"] = round(
                out[f"pp_fit_steps_per_sec_fold{f}"] / base, 3)
    # auto-K through the SAME GroupDispatcher/AutoFoldTuner machinery
    # Model.fit drives: the tuner watches the first dispatches and
    # freezes K from the measured host/device ratio
    tuner = AutoFoldTuner()
    eng._defer_wrapper_sync = True
    try:
        disp = GroupDispatcher(
            lambda groups: (eng.train_steps_folded(groups)[0], []),
            lambda *a: None, fold=1, tuner=tuner)
        for i, (ins, lbs) in enumerate(batches * 2):
            disp.feed(i, ins, lbs)
        disp.flush()
    finally:
        eng._defer_wrapper_sync = False
        eng.sync_to_layers()
    if tuner.decided:
        out["pp_auto_fold"] = tuner.fold
        out["pp_auto_host_ms_per_step"] = \
            tuner.decision["host_ms_per_step"]
        out["pp_auto_device_ms_per_step"] = \
            tuner.decision["device_ms_per_step"]
    _emit_result("pp_fold", out)


def _hlo_dp_collective_bytes(hlo_text, mesh):
    """Bytes-moved proxy from the COMPILED program: per-device WIRE
    bytes of every collective whose replica group spans the dp axis.
    A collective's result size is not its wire cost, so each opcode is
    normalized to the ring/tiled wire volume for its group size W:
    all-reduce = 2*(W-1)/W * result, all-gather = (W-1)/W * result,
    reduce-scatter = (W-1) * result (the per-device result is 1/W of
    the input), collective-permute = result (one hop's payload).
    With that normalization every variant cross-checks the analytic
    `dp_comm_bytes_per_step` model within a few percent;
    `tests/test_hlo_collective_audit` asserts it under pytest."""
    import re
    import numpy as np

    dtype_bytes = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
                   "s32": 4, "u64": 8, "u32": 4, "s8": 1, "u8": 1,
                   "pred": 1, "s16": 2, "u16": 2}

    def decode_groups(attr):
        attr = attr.strip()
        m = re.match(r"\[(\d+),(\d+)\]<=\[([\d,]+)\]"
                     r"(?:T\(([\d,]+)\))?", attr)
        if m:
            g, s = int(m.group(1)), int(m.group(2))
            dims = [int(x) for x in m.group(3).split(",")]
            x = np.arange(int(np.prod(dims))).reshape(dims)
            if m.group(4):
                x = x.transpose([int(p) for p in m.group(4).split(",")])
            return x.reshape(g, s).tolist()
        if attr.startswith("{"):
            return [[int(v) for v in grp.split(",")]
                    for grp in re.findall(r"\{([\d,\s]+)\}", attr)
                    if grp.strip()]
        raise ValueError(f"unparsed replica_groups: {attr!r}")

    def result_bytes(line):
        m = re.search(
            r"=\s*(.*?)\s*(?:all-reduce|reduce-scatter|all-gather|"
            r"collective-permute|all-to-all)(?:-start|-done)?\(", line)
        if not m:
            return 0
        total = 0
        for dt, shp in re.findall(r"(\w+)\[([\d,]*)\]", m.group(1)):
            if dt not in dtype_bytes:
                continue
            n = 1
            for d in shp.split(","):
                if d:
                    n *= int(d)
            total += n * dtype_bytes[dt]
        return total

    axis_names = list(mesh.axis_names)
    dp_axis = axis_names.index("dp")
    coord_of = {i: np.unravel_index(i, mesh.devices.shape)
                for i in range(mesh.devices.size)}

    def spans_dp(device_ids):
        coords = [coord_of[d] for d in device_ids]
        return len({c[dp_axis] for c in coords}) > 1

    def wire_factor(line, group_size):
        w = max(group_size, 2)
        if "all-reduce" in line:
            return 2.0 * (w - 1) / w
        if "all-gather" in line:
            return (w - 1) / w
        if "reduce-scatter" in line:
            return float(w - 1)
        return 1.0                        # collective-permute: one hop

    total = 0.0
    for line in hlo_text.splitlines():
        if "replica_groups=" in line:
            mg = re.search(
                r"replica_groups=(\{\{[^}]*\}[^)]*\}|\[[^ ]+)", line)
            if not mg:
                continue
            try:
                groups = decode_groups(mg.group(1))
            except ValueError:
                continue
            if spans_dp(groups[0]):
                total += result_bytes(line) * wire_factor(
                    line, len(groups[0]))
        elif "source_target_pairs=" in line:
            # the explicit ring's hops: one collective-permute per hop,
            # its result IS the wire payload of that hop
            pairs = re.findall(r"\{(\d+),(\d+)\}", line)
            if pairs and any(spans_dp([int(a), int(b)])
                             for a, b in pairs):
                total += result_bytes(line)
    return int(total)


def bench_dp_compressed():
    """Compressed + sharded dp gradient path on the CPU mesh
    (ISSUE 11 / DESIGN-DCN.md §Strategy knobs): sweep
    {off, bits=16, bits=8} x {sharded update on/off}, recording per
    variant: steps/s (interleaved medians, like the mesh-fold sweep),
    the modeled per-device dp wire bytes per step AND the compiled-HLO
    bytes-moved proxy that cross-checks it, per-replica opt_state
    bytes (the 1/dp memory win), a bits=16-vs-off end-loss parity bit,
    and the DESIGN-DCN simulated scaling efficiency at 256 chips for
    each wire format (the >=90% north-star gate)."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.runner import DistributedRunner

    print("devices-ok", jax.devices(), flush=True)
    dp = int(os.environ.get("GRAFT_BENCH_DP", "2"))
    reps = int(os.environ.get("GRAFT_BENCH_DP_REPS", "3"))
    # leaves >> the 256-elt quantization block so block padding is
    # negligible and the HLO bytes proxy is comparable to the model
    def build():
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(256, 512), nn.ReLU(),
                            nn.Linear(512, 64))
        opt = optimizer.Adam(1e-3, parameters=net.parameters())
        return net, opt

    rng = np.random.RandomState(0)
    batches = [([rng.rand(16, 256).astype(np.float32)],
                [rng.randint(0, 64, (16,)).astype(np.int64)])
               for _ in range(24)]
    variants = [(0, False), (16, False), (8, False),
                (0, True), (16, True), (8, True)]
    runners, final_loss, audits = {}, {}, {}
    mesh = collective.build_mesh({"dp": dp})
    collective.set_mesh(mesh)
    t0 = time.perf_counter()
    for bits, shard in variants:
        net, opt = build()
        r = DistributedRunner(net, opt, nn.CrossEntropyLoss(),
                              mesh=mesh, dp_compress_bits=bits,
                              dp_shard_update=shard)
        hlo = r.lower_step(*batches[0]).compile().as_text()
        audits[(bits, shard)] = _hlo_dp_collective_bytes(hlo, mesh)
        for ins, lbs in batches:                  # warmup epoch
            loss = r.train_step(ins, lbs)
        final_loss[(bits, shard)] = float(loss)
        runners[(bits, shard)] = r
    compile_warmup_s = round(time.perf_counter() - t0, 2)

    samples = {v: [] for v in variants}
    for _ in range(reps):
        for v in variants:                        # interleaved medians
            r = runners[v]
            t0 = time.perf_counter()
            for ins, lbs in batches:
                r.train_step(ins, lbs)
            jax.block_until_ready(r._opt_state)
            samples[v].append(len(batches) /
                              (time.perf_counter() - t0))

    # simulated scaling efficiency (scripts/scaling_projection.py's
    # grounded model, GPT-2-small measured step time) per wire format
    import importlib.util as _ilu
    spec = _ilu.spec_from_file_location(
        "scaling_projection",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "scripts", "scaling_projection.py"))
    proj = _ilu.module_from_spec(spec)
    spec.loader.exec_module(proj)

    grad_elems = sum(int(np.prod(p.shape))
                     for p in runners[(0, False)].network.parameters()
                     if not p.stop_gradient)
    out = {"dp_compressed_dp": dp,
           "dp_compressed_compile_warmup_s": compile_warmup_s,
           "dp_compressed_grad_elems": grad_elems,
           "dp_compressed_bits16_end_loss_parity": (
               final_loss[(16, False)] == final_loss[(0, False)]),
           "dp_compressed_bits8_end_loss_delta": round(
               abs(final_loss[(8, False)] - final_loss[(0, False)]),
               6)}
    for wire, label in (("f32", "off"), ("int8", "int8")):
        out[f"dp_sim_scaling_eff_256chips_{label}"] = round(
            proj.efficiency(132.0, 124e6, 256, wire), 4)
    for (bits, shard), vals in samples.items():
        tag = f"b{bits}_{'sharded' if shard else 'replicated'}"
        med = sorted(vals)[len(vals) // 2]
        out[f"dp_steps_per_sec_{tag}"] = round(med, 1)
        out[f"dp_hlo_bytes_{tag}"] = audits[(bits, shard)]
        # the runner's own per-leaf model (replicated-fallback leaves
        # modeled as the full all-reduce they actually run)
        r = runners[(bits, shard)]
        out[f"dp_model_bytes_{tag}"] = \
            r._dp_comm_info["bytes_per_step"]
        st_bytes = 0
        for st in r._opt_state.values():
            for v in st.values():
                st_bytes += max(
                    s.data.nbytes for s in v.addressable_shards)
        out[f"dp_opt_state_bytes_per_rank_{tag}"] = st_bytes
    _emit_result("dp_compressed", out)


def bench_serving():
    """Continuous-batching decode server under Poisson arrivals
    (ISSUE 6) — CPU by DESIGN like bench_hapi, so the number stays
    comparable while the axon TPU tunnel is down and tracks the HOST
    side of the serving loop: admission, prefill bucketing, page-table
    staging, dispatch, lazy streaming.

    Reports generated tokens/s, request-latency p50/p99 and TTFT under
    a Poisson open-loop arrival process on a tiny GPT config, plus the
    compile/warmup wall-time breakdown — cold-start is a product
    metric (ROADMAP): a serving fleet redeploying under traffic pays
    it on every process, so it is recorded every round exactly like
    steps/s.  ``PADDLE_TPU_COMPILE_CACHE`` (persistent XLA cache)
    shows up directly in these numbers on a second run."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny
    from paddle_tpu.inference.serving import LLMServer

    print("devices-ok", jax.devices(), flush=True)
    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))
    n_requests = 8 if tiny else int(
        os.environ.get("GRAFT_BENCH_SERVING_REQUESTS", "48"))
    mean_interarrival_s = 0.004    # Poisson open loop, ~250 req/s
    max_tokens = 4 if tiny else 16

    paddle.seed(0)
    net = GPTForCausalLM(gpt_tiny(use_flash_attention=False))
    net.eval()
    t0 = time.perf_counter()
    server = LLMServer(net, max_batch=8, block_size=8, num_blocks=256,
                       max_queue=max(64, n_requests),
                       auto_start=False)
    warm = server.warmup()          # every prefill bucket + decode
    compile_warmup_s = time.perf_counter() - t0
    server.start()

    rng = np.random.RandomState(0)
    gaps = rng.exponential(mean_interarrival_s, size=n_requests)
    lengths = rng.randint(4, 49, size=n_requests)
    futs = []
    t_start = time.perf_counter()
    for i in range(n_requests):
        time.sleep(float(gaps[i]))
        prompt = rng.randint(0, 256, size=int(lengths[i])).tolist()
        futs.append(server.submit(prompt, max_tokens=max_tokens))
    results = [f.result(timeout=300) for f in futs]
    wall = time.perf_counter() - t_start
    stats = server.stats()
    server.close()

    total_tokens = sum(len(r.tokens) for r in results)
    lats = sorted(r.stats.latency for r in results)
    ttfts = sorted(r.stats.ttft for r in results)

    def pct(sorted_vals, q):
        # exact percentile over this run's request list (PR 8 removed
        # the server's private _percentile ring when stats() re-backed
        # onto registry histograms; the bench keeps exact per-run
        # numbers from the futures it already holds)
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1,
                max(0, int(round(q / 100 * (len(sorted_vals) - 1)))))
        return float(sorted_vals[i])

    _emit_result("serving", {
        "serving_tokens_per_sec": round(total_tokens / wall, 1),
        "serving_requests_per_sec": round(n_requests / wall, 1),
        "serving_p50_latency_ms": round(pct(lats, 50) * 1e3, 1),
        "serving_p99_latency_ms": round(pct(lats, 99) * 1e3, 1),
        "serving_p50_ttft_ms": round(pct(ttfts, 50) * 1e3, 1),
        "serving_p99_ttft_ms": round(pct(ttfts, 99) * 1e3, 1),
        "serving_compile_warmup_s": round(compile_warmup_s, 2),
        "serving_decode_compile_s": warm["decode_compile_s"],
        "serving_requests": n_requests,
        "serving_max_tokens": max_tokens,
        "serving_dispatches": stats["dispatches"],
        "serving_decode_traces": stats["decode_traces"],
        "serving_kv_fragmentation": round(
            stats["kv"]["fragmentation"], 3),
    })


def bench_spec():
    """Speculative decoding (ISSUE 19) — CPU host-loop proxy.

    On a TPU deployment the decode loop is HOST-bound: the per-token
    device forward is microseconds while Python dispatch, streaming,
    and the done-poll sync cost milliseconds — speculation's whole win
    is doing that host round-trip once per k+1 tokens.  This bench
    reproduces that regime on CPU with a deliberately tiny model
    (device forward ~1 ms) and a LIVE streaming consumer that reads
    each token as it arrives (the SSE-server pattern: one lazy-stack
    materialization per dispatch) — so tokens/s tracks host
    round-trips per token, exactly what speculation collapses.

    Matrix: k in {2, 4, 8} x {self-draft (accept ~1, the headline),
    adversarial draft (sign-flipped weights, accept ~0, the floor)}
    against the non-speculative engine on the same closed-loop load.
    Every leg is steady-state (a full warm round first, so compile
    time never pollutes the ratio) and token-identical to the
    baseline by the exactness contract (tests/test_serving_spec.py).
    Reports tokens/s per request, speedup, lane-normalized
    dispatches/token (from serving_spec_dispatches_total), and the
    measured accept rate."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny
    from paddle_tpu.inference.serving import (DecodeEngine,
                                              extract_decode_params,
                                              filter_spec_stream)

    print("devices-ok", jax.devices(), flush=True)
    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))
    B = 4
    max_tokens = 16 if tiny else 96
    ks = (2,) if tiny else (2, 4, 8)

    paddle.seed(0)
    # host-loop proxy config: 1 layer / hidden 32 keeps the device
    # forward ~1 ms so the host round-trip dominates, as on TPU
    cfg = gpt_tiny(use_flash_attention=False, num_hidden_layers=1,
                   hidden_size=32, num_attention_heads=2,
                   intermediate_size=64)
    net = GPTForCausalLM(cfg)
    net.eval()
    params = extract_decode_params(net)
    # adversarial draft: sign-flipped weights share the geometry but
    # never agree with the target's argmax — the accept ~0 floor
    neg = jax.tree_util.tree_map(lambda a: -a, params)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 256, (12,)).tolist() for _ in range(B)]

    def mkcb(spec):
        raw = lambda rid, idx, tok: int(tok)       # live consumer
        return (filter_spec_stream(raw, max_tokens=max_tokens)
                if spec else raw)

    def warm(eng, spec):
        for p in prompts:                  # warm round: every program
            eng.submit(p, max_tokens=max_tokens, stream_cb=mkcb(spec))
        eng.run_until_idle()

    def timed(eng, spec):
        d0 = eng._dispatch_count
        futs = [eng.submit(p, max_tokens=max_tokens,
                           stream_cb=mkcb(spec)).future
                for p in prompts]
        t0 = time.perf_counter()
        eng.run_until_idle()
        wall = time.perf_counter() - t0
        toks = sum(len(f.result(timeout=0).tokens) for f in futs)
        return wall, toks, eng._dispatch_count - d0

    # the baseline engine stays alive the whole matrix and every leg
    # re-times it back-to-back with its spec rounds (best of 3 each):
    # single-core wall noise drifts over the minutes this bench runs,
    # and pairing the rounds in time cancels it in the RATIO — an
    # up-front baseline against a late leg does not
    base_eng = DecodeEngine(net, max_batch=B, block_size=8,
                            num_blocks=256)
    warm(base_eng, False)
    out = {"spec_max_tokens": max_tokens, "spec_batch": B}
    base_best = None
    best = 0.0
    for k in ks:
        for name, dp in (("self", params), ("adv", neg)):
            eng = DecodeEngine(net, max_batch=B, block_size=8,
                               num_blocks=256, draft_params=dp,
                               spec_k=k)
            warm(eng, True)
            wb = ws = None
            for _ in range(3):
                b = timed(base_eng, False)
                s = timed(eng, True)
                if wb is None or b[0] < wb[0]:
                    wb = b
                if ws is None or s[0] < ws[0]:
                    ws = s
            if base_best is None or wb[0] < base_best[0]:
                base_best = wb
            w, t, d = ws
            sp = eng.stats()["spec"]
            speedup = (t / w) / (wb[1] / wb[0])
            # lane-normalized dispatches per committed token over the
            # timed round (the delta of serving_spec_dispatches_total
            # across it): all B lanes run the whole closed-loop round,
            # so lanes = dispatches * B
            dpt = d * B / t
            key = f"spec_k{k}_{name}"
            out[f"{key}_tokens_per_sec_per_request"] = round(
                t / w / B, 1)
            out[f"{key}_speedup"] = round(speedup, 2)
            out[f"{key}_dispatches_per_token"] = round(dpt, 3)
            out[f"{key}_accept_rate"] = round(sp["accept_rate"], 3)
            if name == "self":
                best = max(best, speedup)
    out["spec_baseline_tokens_per_sec_per_request"] = round(
        base_best[1] / base_best[0] / B, 1)
    out["spec_baseline_dispatches_per_token"] = round(
        base_best[2] * B / base_best[1], 3)
    out["spec_best_self_speedup"] = round(best, 2)
    _emit_result("spec", out)


def bench_longcontext():
    """Long-context serving tier (ISSUE 14) — CPU by design like the
    serving bench.  Three sub-rounds:

    (a) a ~32k-token prompt admitted through CHUNKED prefill and
        decoded through the fused paged-attention kernel (Pallas,
        interpret mode on this container) — the round that cannot
        exist on the gather composition's memory story: the analytic
        per-layer attention working set of gather
        (``[B, MAXNB*BS, H, Dh]`` K+V) vs the kernel's
        one-block-per-request residency is recorded as the ratio;
    (b) a shared-system-prompt request mix: prefix-cache hit rate and
        prompt tokens whose prefill was skipped outright;
    (c) chunked-prefill tail impact: p99 inter-token gap of a RUNNING
        decode stream while a long prompt admits, chunked vs
        whole-prompt — the latency cliff chunking exists to remove.
    """
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny
    from paddle_tpu.inference.serving import DecodeEngine
    from paddle_tpu.inference.serving.paged_attention_kernel import (
        attention_working_set_bytes)

    print("devices-ok", jax.devices(), flush=True)
    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))
    CTX = 2048 if tiny else int(
        os.environ.get("GRAFT_BENCH_LONGCONTEXT", "32768"))
    BS = 64 if tiny else 256            # KV block size
    CHUNK = 256 if tiny else 1024       # prefill admission unit
    paddle.seed(0)
    cfg = gpt_tiny(use_flash_attention=False, hidden_size=32,
                   num_attention_heads=2, num_hidden_layers=2,
                   intermediate_size=64,
                   max_position_embeddings=CTX + 2 * BS)
    net = GPTForCausalLM(cfg)
    net.eval()
    out = {"longcontext_context_tokens": CTX,
           "longcontext_block_size": BS,
           "longcontext_prefill_chunk": CHUNK}

    # -- (a) the 32k round: chunked admission + fused-kernel decode --
    eng = DecodeEngine(net, max_batch=2, block_size=BS,
                       num_blocks=CTX // BS + 8, prefill_chunk=CHUNK,
                       prefix_cache=True, attention="pallas")
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (CTX - BS,)).tolist()
    t0 = time.perf_counter()
    fut = eng.submit(prompt, max_tokens=4, temperature=0.8,
                     seed=1).future
    eng.run_until_idle()
    res = fut.result(timeout=0)
    wall = time.perf_counter() - t0
    st = res.stats
    h = eng._h_chunk
    ws = attention_working_set_bytes(
        eng.max_batch, eng.max_blocks_per_seq, BS,
        cfg.num_attention_heads,
        cfg.hidden_size // cfg.num_attention_heads)
    decode_s = (st.latency or 0) - (st.ttft or 0)
    out.update({
        "longcontext_attention": eng.attention_mode,
        "longcontext_round_wall_s": round(wall, 2),
        "longcontext_ttft_s": round(st.ttft or 0, 2),
        "longcontext_chunks": int(h.collect()["count"]),
        "longcontext_chunk_p50_s": round(h.quantile(0.50), 4),
        "longcontext_chunk_p99_s": round(h.quantile(0.99), 4),
        "longcontext_decode_tok_per_s": round(
            (len(res.tokens) - 1) / decode_s, 2) if decode_s else None,
        "longcontext_gather_workset_mb": round(
            ws["gather_bytes"] / 1e6, 2),
        "longcontext_kernel_workset_mb": round(
            ws["kernel_bytes"] / 1e6, 2),
        "longcontext_workset_ratio": ws["ratio"],
        "longcontext_decode_traces": eng.compile_stats()
        ["decode_traces"],
    })

    # -- (b) shared-system-prompt mix: prefix-cache hit rate --------
    eng2 = DecodeEngine(net, max_batch=4, block_size=16,
                        num_blocks=256, prefill_chunk=128,
                        prefix_cache=True)
    system = rng.randint(0, cfg.vocab_size, (512,)).tolist()
    n_req = 4 if tiny else 12
    t0 = time.perf_counter()
    futs = []
    for _ in range(n_req):
        user = rng.randint(0, cfg.vocab_size, (16,)).tolist()
        futs.append(eng2.submit(system + user, max_tokens=4).future)
        eng2.run_until_idle()
    for f in futs:
        f.result(timeout=0)
    pstats = eng2._prefix.stats()
    out.update({
        "longcontext_prefix_requests": n_req,
        "longcontext_prefix_hit_rate": round(pstats["hit_rate"], 3),
        "longcontext_prefix_tokens_skipped": int(
            pstats["hits"] * 16),
        "longcontext_prefix_wall_s": round(
            time.perf_counter() - t0, 2),
    })

    # -- (c) chunked-prefill p99 impact on a running decode ---------
    big_len = min(4096, CTX) - 64

    def gap_p99(prefill_chunk):
        e = DecodeEngine(net, max_batch=2, block_size=64,
                         num_blocks=CTX // 64 + 16,
                         prefill_chunk=prefill_chunk)
        # warm pass: compile every prefill/chunk/decode trace this
        # measurement touches — the steady-state question is dispatch
        # interleaving, not cold-start (which (a) already records)
        for warm in (False, True):
            arrivals = []
            fa = e.submit(
                rng.randint(0, cfg.vocab_size, (8,)).tolist(),
                max_tokens=48,
                stream_cb=lambda rid, i, t: arrivals.append(
                    time.monotonic())).future
            for _ in range(4):
                e.step()                  # decode stream running
            big = e.submit(rng.randint(
                0, cfg.vocab_size, (big_len,)).tolist(),
                max_tokens=2).future
            e.run_until_idle()
            fa.result(timeout=0)
            big.result(timeout=0)
        gaps = sorted(b - a for a, b in zip(arrivals, arrivals[1:]))
        return gaps[min(len(gaps) - 1,
                        int(round(0.99 * (len(gaps) - 1))))]

    out["longcontext_decode_gap_p99_ms_whole"] = round(
        gap_p99(None) * 1e3, 1)
    out["longcontext_decode_gap_p99_ms_chunked"] = round(
        gap_p99(512) * 1e3, 1)
    _emit_result("longcontext", out)


def bench_disagg():
    """Disaggregated prefill/decode serving (ISSUE 16) — CPU by
    design like the other serving benches.  Two sub-rounds:

    (a) running-decode p99 inter-token gap while a ~32k prompt is
        admitted on a SEPARATE prefill replica and handed off as a
        page migration — the number this tier exists for: chunked
        prefill (PR 14) got the single-engine gap from 1281 ms to
        88 ms; moving admission off the decode replica entirely is
        supposed to beat that (the residual jitter was exactly the
        chunks still sharing the decode dispatch queue);
    (b) a mixed long/short Poisson arrival process through the full
        disaggregated pipeline vs the same process on one
        both-phases engine — handoff overhead must not cost
        throughput.

    Both replicas live in this one process, so without isolation they
    share ONE XLA host device: every computation serializes on that
    device's execution queue, and a late 32k chunk (a multi-second
    computation here) blocks the decode step queued behind it — the
    resource coupling disaggregation removes by putting phases on
    separate chips, and exactly what this bench must not re-measure.
    The in-process stand-in is two forced host devices with each
    replica pinned to its own (``LLMServer(device=...)``) plus
    single-threaded eigen so the two devices' computations don't fight
    over cores either: one replica's chunk occupies one core while
    the decode stream keeps dispatching on the other.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
        + " --xla_cpu_multi_thread_eigen=false"
        + " intra_op_parallelism_threads=1").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny
    from paddle_tpu.inference.serving import DisaggRouter, LLMServer

    print("devices-ok", jax.devices(), flush=True)
    tiny = bool(os.environ.get("GRAFT_BENCH_TINY"))
    CTX = 2048 if tiny else int(
        os.environ.get("GRAFT_BENCH_LONGCONTEXT", "32768"))
    BS = 64 if tiny else 256            # KV block size
    CHUNK = 256 if tiny else 1024       # prefill admission unit
    stream_cap = 4096 if tiny else 16384   # running-stream budget
    paddle.seed(0)
    cfg = gpt_tiny(use_flash_attention=False, hidden_size=32,
                   num_attention_heads=2, num_hidden_layers=2,
                   intermediate_size=64,
                   max_position_embeddings=max(CTX, stream_cap)
                   + 2 * BS)
    net = GPTForCausalLM(cfg)
    net.eval()
    rng = np.random.RandomState(0)
    out = {"disagg_context_tokens": CTX, "disagg_block_size": BS,
           "disagg_prefill_chunk": CHUNK}

    # -- (a) running-decode gap under a 32k admission ---------------
    # the decode pool holds the stream's WORST CASE (its reservation)
    # next to the migrated big request; the prefill pool only ever
    # needs prompt blocks (prefill-role admission envelope)
    nb_pre = CTX // BS + 24
    nb_dec = CTX // BS + stream_cap // BS + 24
    dev_pre, dev_dec = jax.devices()[0], jax.devices()[-1]
    router = DisaggRouter(
        lambda: LLMServer(net, max_batch=2, block_size=BS,
                          num_blocks=nb_pre, role="prefill",
                          prefill_chunk=CHUNK, prefix_cache=False,
                          device=dev_pre),
        lambda: LLMServer(net, max_batch=2, block_size=BS,
                          num_blocks=nb_dec, role="decode",
                          prefix_cache=False, device=dev_dec),
        prefill_pool={"decision_interval_s": 0},
        decode_pool={"decision_interval_s": 0})
    big_prompt = rng.randint(0, cfg.vocab_size, (CTX - BS,)).tolist()

    # warm + calibrate: one short request end-to-end compiles the
    # chunk/export/decode/import/join paths for SHORT shapes and
    # measures the steady-state decode gap; one full-size admission
    # compiles every context-bucket chunk trace AND the big import
    # bucket, and measures the admission wall the measured stream
    # must outlive
    arrivals = []
    router.submit(
        rng.randint(0, cfg.vocab_size, (8,)).tolist(), max_tokens=64,
        stream_cb=lambda rid, i, t: arrivals.append(time.monotonic())
    ).result(timeout=600)
    gaps = sorted(b - a for a, b in zip(arrivals, arrivals[1:]))
    gap_p50 = gaps[len(gaps) // 2]
    t0 = time.perf_counter()
    router.submit(big_prompt, max_tokens=2).result(timeout=1200)
    admit_wall = time.perf_counter() - t0

    # measured round: a running decode stream sized to outlive the
    # whole admission (1.5x margin on the calibrated walls)
    stream_tokens = int(min(stream_cap, max(
        128, 1.5 * admit_wall / max(gap_p50, 1e-4))))
    arrivals = []
    f_stream = router.submit(
        rng.randint(0, cfg.vocab_size, (8,)).tolist(),
        max_tokens=stream_tokens,
        stream_cb=lambda rid, i, t: arrivals.append(time.monotonic()))
    deadline = time.monotonic() + 300
    while len(arrivals) < 8 and time.monotonic() < deadline:
        time.sleep(0.002)
    t_admit = time.monotonic()
    big = router.submit(big_prompt, max_tokens=2)
    big.result(timeout=1200)
    t_done = time.monotonic()
    f_stream.result(timeout=1200)
    window = [t for t in arrivals if t_admit <= t <= t_done]
    wgaps = sorted(b - a for a, b in zip(window, window[1:]))
    dec_server = router.decode.replicas[0]
    dst = dec_server.engine.stats()
    out.update({
        "disagg_admit_wall_s": round(admit_wall, 2),
        "disagg_stream_tokens": stream_tokens,
        "disagg_gap_samples_in_window": len(wgaps),
        "disagg_decode_gap_p50_ms": round(
            wgaps[len(wgaps) // 2] * 1e3, 1) if wgaps else None,
        "disagg_decode_gap_p99_ms": round(
            wgaps[min(len(wgaps) - 1,
                      int(round(0.99 * (len(wgaps) - 1))))] * 1e3, 1)
        if wgaps else None,
        "disagg_page_migrations": int(
            dec_server.engine._c_migrations.collect()),
        "disagg_migrated_blocks": int(
            dec_server.engine._c_migrated_blocks.collect()),
        "disagg_migration_p50_s": round(
            dec_server.engine._h_migration.quantile(0.50), 4),
        "disagg_decode_traces": dec_server.engine.compile_stats()
        ["decode_traces"],
    })
    router.close()

    # -- (b) mixed Poisson tok/s: disaggregated vs single engine ----
    n_req = 6 if tiny else 24
    long_len = 128 if tiny else 512

    def poisson_mix(submit, seed):
        r = np.random.RandomState(seed)
        futs = []
        t0 = time.perf_counter()
        for i in range(n_req):
            L = long_len if i % 3 == 0 else 16
            p = r.randint(0, cfg.vocab_size, (L,)).tolist()
            futs.append(submit(p, max_tokens=16))
            time.sleep(float(r.exponential(0.03)))
        toks = sum(len(f.result(timeout=600).tokens) for f in futs)
        return toks / (time.perf_counter() - t0)

    mix_kw = dict(block_size=16, num_blocks=256, prefill_chunk=128,
                  prefix_cache=False)
    single = LLMServer(net, max_batch=4, **mix_kw)
    single.submit([1, 2, 3], max_tokens=4).result(timeout=600)  # warm
    single_tps = poisson_mix(single.submit, seed=7)
    single.close()
    router2 = DisaggRouter(
        lambda: LLMServer(net, max_batch=4, role="prefill", **mix_kw),
        lambda: LLMServer(net, max_batch=4, role="decode", **mix_kw),
        prefill_pool={"decision_interval_s": 0},
        decode_pool={"decision_interval_s": 0})
    router2.submit([1, 2, 3], max_tokens=4).result(timeout=600)
    disagg_tps = poisson_mix(router2.submit, seed=7)
    router2.close()
    out.update({
        "disagg_mix_requests": n_req,
        "disagg_mix_tok_per_s": round(disagg_tps, 1),
        "disagg_mix_single_tok_per_s": round(single_tps, 1),
        "disagg_mix_vs_single": round(disagg_tps / single_tps, 3)
        if single_tps else None,
    })
    _emit_result("disagg", out)


# Fleet-bench worker: two beacon-publishing ranks with per-rank step
# pace, scraped from OUTSIDE over the controller's /fleet/* plane.
# Deliberately jax-free: what this bench measures is the
# observability plane itself (scrape + merge + straggler
# attribution), not device throughput.
_FLEET_WORKER = '''
import json, os, time
import paddle_tpu  # arms the per-rank /metrics endpoint from env
from paddle_tpu.distributed.resilience.elastic_rank import (
    ElasticRankContext)
from paddle_tpu.observability import metrics, trace

ctx = ElasticRankContext.from_env()
assert ctx is not None
ctx.register()
rank = ctx.rank
sleep_s = float(os.environ["FLEET_STEP_SLEEP"].split(",")[rank])
stop_file = os.environ["FLEET_STOP_FILE"]
reg = metrics.registry()
steps = reg.counter("fit_steps_total", "committed steps")
for step in range(1, 2000):
    with trace.span("train.step", {"rank": rank}):
        time.sleep(sleep_s)
    steps.inc()
    ctx.publish_beacon(step=step)
    if os.path.exists(stop_file):
        break
ctx.exit()
print(f"FLEET-WORKER-DONE rank={rank}", flush=True)
'''


def bench_fleet():
    """The distributed observability plane, measured end to end
    (ISSUE 10): a REAL ``launch --nproc_per_node 2 --metrics_port``
    run answered entirely over HTTP from outside — per-rank /metrics
    with rank labels, the controller's /fleet/metrics merge, the
    pid-per-rank /fleet/trace, and straggler attribution of an
    artificially slowed rank 1.  The record attaches ONE merged fleet
    snapshot (the controller's /fleet/metrics.json), not per-child
    dump files — the fleet answer IS the product here."""
    import socket
    import tempfile
    import urllib.request

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    here = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="bench_fleet_")
    script = os.path.join(work, "fleet_worker.py")
    with open(script, "w") as f:
        f.write(_FLEET_WORKER)
    stop_file = os.path.join(work, "stop")
    base = free_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PADDLE_TPU_TRACE": "1",
        "FLEET_STEP_SLEEP": "0.05,0.25",   # rank 1 is the straggler
        "FLEET_STOP_FILE": stop_file,
        "PYTHONPATH": here + os.pathsep + env.get("PYTHONPATH", ""),
    })
    t0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--metrics_port", str(base),
         "--job_id", "bench-fleet", "--log_dir",
         os.path.join(work, "log"), script],
        env=env, cwd=work, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    def get_json(port, path, timeout=1.0):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}",
                timeout=timeout) as r:
            return json.loads(r.read().decode())

    out = {"fleet_ranks": 2}
    merged = None
    straggler = None
    deadline = time.time() + 120
    try:
        while time.time() < deadline:
            time.sleep(0.5)
            if proc.poll() is not None:
                break
            try:
                snap = get_json(base, "/fleet/metrics.json")
                ctl = get_json(base, "/metrics.json")["metrics"]
            except OSError:
                continue
            except ValueError:
                continue
            have_sum = snap.get("fit_steps_total", {}).get("value", 0)
            flag = ctl.get('fleet_straggler{rank="1"}',
                           {}).get("value")
            if have_sum and have_sum >= 20 and flag == 1.0:
                merged = snap
                straggler = ctl
                break
        if merged is not None:
            out["fleet_scrape_to_straggler_s"] = round(
                time.perf_counter() - t0, 2)
            out["fleet_fit_steps_total"] = merged[
                "fit_steps_total"]["value"]
            # per-rank /metrics answers with the rank label; a rank
            # whose endpoint failed to bind (http arming degrades,
            # never kills the worker) records False instead of
            # killing the whole record
            for r in (0, 1):
                try:
                    txt = urllib.request.urlopen(
                        f"http://127.0.0.1:{base + 1 + r}/metrics",
                        timeout=2).read().decode()
                    out[f"fleet_rank{r}_has_rank_label"] = (
                        f'rank="{r}"' in txt)
                except OSError:
                    out[f"fleet_rank{r}_has_rank_label"] = False
            try:
                trace_json = get_json(base, "/fleet/trace",
                                      timeout=10.0)
                out["fleet_trace_pids"] = sorted(
                    {e["pid"] for e in trace_json["traceEvents"]})
            except (OSError, ValueError) as e:
                out["fleet_trace_error"] = f"{type(e).__name__}: {e}"
            out["fleet_straggler_rank1_step_time_s"] = straggler[
                'fleet_rank_step_time_s{rank="1"}']["value"]
            # ONE merged fleet snapshot, not per-child dumps
            path = os.path.join(here, ".bench_obs", "fleet.json")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump({"fleet_metrics": merged,
                           "controller_metrics": straggler}, f,
                          indent=1)
            out["obs_snapshot_fleet"] = path
        else:
            out["fleet_error"] = "plane never converged in 120s"
    finally:
        with open(stop_file, "w") as f:
            f.write("1")
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()          # reap, so returncode is real
    out["fleet_launch_rc"] = proc.returncode
    print("RESULT " + json.dumps(out), flush=True)


# Self-heal bench worker: beacon-publishing ranks with per-MEMBER
# pace (the original rank-1 member is the straggler; the spare that
# replaces it runs at fleet pace), so the bench measures the action
# loop itself — latency onset → drain verdict → promotion → fleet
# step-time recovered — with no jax compile noise in the timeline.
_SELFHEAL_WORKER = '''
import os, time
import paddle_tpu  # arms the per-rank /metrics endpoint from env
from paddle_tpu.distributed.resilience.elastic_rank import (
    ElasticRankContext)

ctx = ElasticRankContext.from_env()
assert ctx is not None
ctx.register()
if ctx.role == "spare":
    ticket = ctx.wait_for_promotion()
    if ticket is None:
        ctx.exit()
        raise SystemExit(0)
slow_member = os.environ["SELFHEAL_SLOW_MEMBER"]
pace = (float(os.environ["SELFHEAL_SLOW_S"])
        if ctx.member_id == slow_member
        else float(os.environ["SELFHEAL_FAST_S"]))
stop_file = os.environ["SELFHEAL_STOP_FILE"]
for step in range(1, 100000):
    time.sleep(pace)
    ctx.publish_beacon(step=step)
    if os.path.exists(stop_file):
        break
ctx.exit()
print(f"SELFHEAL-WORKER-DONE member={ctx.member_id}", flush=True)
'''


def bench_selfheal():
    """The observability→action loop, measured end to end (ISSUE 13):
    a REAL ``launch --spares 1 --drain_stragglers`` run where the
    original rank 1 steps 5x slower than the fleet.  The record is
    the loop's reaction time, scraped from OUTSIDE over the
    controller plane: ``selfheal_to_drain_s`` (launch → drain
    decision on /fleet/events) and ``selfheal_drain_to_recovered_s``
    (drain → the promoted successor's step-time back under the
    straggler bar on the controller registry)."""
    import socket
    import tempfile
    import urllib.request

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    here = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="bench_selfheal_")
    script = os.path.join(work, "selfheal_worker.py")
    with open(script, "w") as f:
        f.write(_SELFHEAL_WORKER)
    stop_file = os.path.join(work, "stop")
    base = free_port()
    factor = 2.0
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SELFHEAL_FAST_S": "0.08",
        "SELFHEAL_SLOW_S": "0.4",       # 5x the fleet pace
        "SELFHEAL_SLOW_MEMBER": "rank-1",
        "SELFHEAL_STOP_FILE": stop_file,
        "PYTHONPATH": here + os.pathsep + env.get("PYTHONPATH", ""),
    })
    t0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--spares", "1",
         "--metrics_port", str(base),
         "--straggler_factor", str(factor),
         "--drain_stragglers", "8",
         "--beacon_timeout", "30",     # only the drain may replace
         "--job_id", "bench-selfheal",
         "--log_dir", os.path.join(work, "log"), script],
        env=env, cwd=work, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    def get_json(path, timeout=1.0):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{base}{path}",
                timeout=timeout) as r:
            return json.loads(r.read().decode())

    out = {"selfheal_slow_factor": 5.0,
           "selfheal_drain_windows": 8}
    t_drain = t_recovered = None
    deadline = time.time() + 120
    try:
        while time.time() < deadline and proc.poll() is None:
            time.sleep(0.25)
            try:
                if t_drain is None:
                    ev = get_json("/fleet/events")
                    if any(e.get("kind") == "drain"
                           for e in ev.get("events", [])):
                        t_drain = time.perf_counter()
                    continue
                # after the drain: recovered when the successor holds
                # a step-time estimate back under the straggler bar
                ctl = get_json("/metrics.json")["metrics"]
                st1 = ctl.get('fleet_rank_step_time_s{rank="1"}',
                              {}).get("value")
                st0 = ctl.get('fleet_rank_step_time_s{rank="0"}',
                              {}).get("value")
                flag = ctl.get('fleet_straggler{rank="1"}',
                               {}).get("value")
                if (st0 and st1 and flag == 0.0
                        and st1 < factor * st0):
                    t_recovered = time.perf_counter()
                    break
            except (OSError, ValueError):
                continue
        if t_drain is not None:
            out["selfheal_to_drain_s"] = round(t_drain - t0, 2)
        else:
            out["selfheal_error"] = "no drain decision in 120s"
        if t_recovered is not None:
            out["selfheal_drain_to_recovered_s"] = round(
                t_recovered - t_drain, 2)
            out["selfheal_total_s"] = round(t_recovered - t0, 2)
            try:
                h = get_json("/fleet/healthz")
                out["selfheal_quarantined_total"] = \
                    h["quarantined_total"]
                out["selfheal_spares_available"] = \
                    h["spares_available"]
            except (OSError, ValueError):
                pass
        elif t_drain is not None:
            out["selfheal_error"] = "drained but never recovered"
    finally:
        with open(stop_file, "w") as f:
            f.write("1")
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()          # reap, so returncode is real
    out["selfheal_launch_rc"] = proc.returncode
    print("RESULT " + json.dumps(out), flush=True)


def bench_selfheal_hosts():
    """Multi-host self-heal (`--selfheal --hosts 2`, ISSUE 18): a
    REAL `launch --nnodes 2` run over two simulated host agents on
    one KV server; SIGKILL of the WHOLE second node (agent + both its
    ranks + its spares) mid-step.  The record is the node-level
    action loop measured from outside over the controller plane:
    ``selfheal_node_death_verdict_s`` (kill → node_death on
    /fleet/events, i.e. the lease-expiry judgment) and
    ``selfheal_node_death_to_recovered_s`` (kill → batch promotion
    complete: no pending failures, every rank id alive again)."""
    import signal
    import socket
    import tempfile
    import urllib.request

    from paddle_tpu.distributed.fleet.elastic import KVClient, KVServer
    from paddle_tpu.distributed.resilience.elastic_rank import kv_key

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    here = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="bench_selfheal_hosts_")
    script = os.path.join(work, "selfheal_worker.py")
    with open(script, "w") as f:
        f.write(_SELFHEAL_WORKER)
    stop_file = os.path.join(work, "stop")
    base = free_port()
    job = "bench-selfheal-hosts"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SELFHEAL_FAST_S": "0.08",
        "SELFHEAL_SLOW_S": "0.08",     # nobody straggles: the fault
        "SELFHEAL_SLOW_MEMBER": "-",   # here is a whole dead node
        "SELFHEAL_STOP_FILE": stop_file,
        "PYTHONPATH": here + os.pathsep + env.get("PYTHONPATH", ""),
    })
    server = KVServer().start()
    client = KVClient(server.endpoint)
    agents = [subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--agent", "--host_id", h, "--elastic_server",
         server.endpoint, "--job_id", job,
         "--log_dir", os.path.join(work, "log")],
        env=env, cwd=work, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for h in ("h0", "h1")]
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "2", "--nproc_per_node", "2", "--spares", "2",
         "--elastic_server", server.endpoint,
         "--metrics_port", str(base),
         "--beacon_timeout", "30",     # only the lease may judge
         "--job_id", job,
         "--log_dir", os.path.join(work, "log"), script],
        env=env, cwd=work, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    def get_json(path, timeout=1.0):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{base}{path}",
                timeout=timeout) as r:
            return json.loads(r.read().decode())

    out = {"selfheal_hosts": 2, "selfheal_world": 4}
    t_kill = t_event = t_recovered = None
    try:
        # wait until every rank on the doomed host is actually
        # stepping (beacon moving), so the kill lands mid-step
        run_id = None
        deadline = time.time() + 90
        while time.time() < deadline and run_id is None:
            try:
                raw = client.get(kv_key(job, "run"))
                if raw:
                    run_id = json.loads(raw)["run_id"]
            except (OSError, ValueError, KeyError):
                pass
            time.sleep(0.25)
        victim_pids = []
        while time.time() < deadline:
            try:
                raw = client.get(kv_key(job, "beacon", "2",
                                        run_id=run_id))
                if raw and json.loads(raw).get("step", 0) >= 2:
                    lease = json.loads(client.get(
                        kv_key(job, "node", "h1", run_id=run_id)))
                    victim_pids = [
                        p["pid"] for p in lease["procs"].values()
                        if p.get("pid") and p.get("rc") is None]
                    break
            except (OSError, ValueError, TypeError, KeyError):
                pass
            time.sleep(0.25)
        if not victim_pids:
            out["selfheal_error"] = "node h1 never reached step 2"
        else:
            agents[1].kill()          # the agent itself…
            for pid in victim_pids:   # …and every process it held
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
            t_kill = time.perf_counter()
            deadline = time.time() + 120
            while time.time() < deadline and proc.poll() is None:
                time.sleep(0.25)
                try:
                    if t_event is None:
                        ev = get_json("/fleet/events")
                        if any(e.get("kind") == "node_death"
                               for e in ev.get("events", [])):
                            t_event = time.perf_counter()
                        continue
                    h = get_json("/fleet/healthz")
                    if (h["epoch"] >= 1 and not h["pending_failures"]
                            and all(m["alive"] or m["quarantined"]
                                    for m in h["members"])
                            and sum(1 for m in h["members"]
                                    if m["alive"]) >= 4):
                        t_recovered = time.perf_counter()
                        break
                except (OSError, ValueError, KeyError):
                    continue
            if t_event is not None:
                out["selfheal_node_death_verdict_s"] = round(
                    t_event - t_kill, 2)
            else:
                out["selfheal_error"] = "no node_death verdict in 120s"
            if t_recovered is not None:
                out["selfheal_node_death_to_recovered_s"] = round(
                    t_recovered - t_kill, 2)
                try:
                    ctl = get_json("/metrics.json")["metrics"]
                    out["selfheal_promotions_total"] = ctl.get(
                        "resilience_promotions_total", {}).get("value")
                except (OSError, ValueError):
                    pass
            elif t_event is not None:
                out["selfheal_error"] = "verdict but never recovered"
    finally:
        with open(stop_file, "w") as f:
            f.write("1")
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()          # reap, so returncode is real
        for a in agents:
            if a.poll() is None:
                try:
                    a.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    a.kill()
                    a.wait()
        server.stop()
    out["selfheal_launch_rc"] = proc.returncode
    print("RESULT " + json.dumps(out), flush=True)


def bench_flash_micro():
    """Pallas flash kernel vs composed XLA attention, fwd+bwd wall time
    per call at seq 1k/4k/8k (VERDICT r2 item 5 microbench line)."""
    _maybe_force_cpu()
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_ops

    print("devices-ok", jax.devices(), flush=True)
    b, h, d = 1, 8, 64
    out = {}
    # on CPU (dryrun) the "pallas" path falls back to the composed form:
    # keep sequences tiny so the O(S^2) bwd can't blow the budget
    seqs = (1024, 4096, 8192) if jax.default_backend() == "tpu" \
        else (256,)
    for s in seqs:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(b * h, s, d).astype(np.float32)
                        ).astype(jnp.bfloat16)
        empty = jnp.zeros((0,), jnp.int32)

        def loss_pallas(q_, k_, v_):
            return pallas_ops._flash_core(q_, k_, v_, empty, empty,
                                          True).astype(jnp.float32).sum()

        def loss_ref(q_, k_, v_):
            return pallas_ops._flash_reference(
                q_, k_, v_, True).astype(jnp.float32).sum()

        for tag, fn in (("pallas", loss_pallas), ("xla", loss_ref)):
            if tag == "xla" and s > 4096:
                continue   # O(S^2) composed bwd at 8k risks OOM/time

            # axon-tunnel-honest timing: identical dispatches get
            # deduped and block_until_ready can return early, so CHAIN
            # the fwd+bwd calls through a data dependency inside ONE
            # jitted program and take the slope between two chain
            # lengths, forcing completion with a host transfer.
            def chain(n, fn=fn):
                def run(q_):
                    def body(carry, _):
                        dq, _dk, _dv = jax.grad(
                            fn, argnums=(0, 1, 2))(carry, carry, carry)
                        return (carry + 1e-3 * dq.astype(carry.dtype)
                                ), None
                    c, _ = jax.lax.scan(body, q_, None, length=n)
                    return c
                j = jax.jit(run)
                r = j(q)
                _ = float(r[0, 0, 0].astype(jnp.float32))  # warm+sync
                t0 = time.perf_counter()
                r = j(q + 1e-4)
                _ = float(r[0, 0, 0].astype(jnp.float32))
                return time.perf_counter() - t0

            n_lo, n_hi = (1, 5) if s >= 4096 else (2, 12)
            per = (chain(n_hi) - chain(n_lo)) / (n_hi - n_lo)
            out[f"flash_{tag}_s{s}_ms"] = round(per * 1000, 2)
    _emit_result("flash", out)


def _parse_result(line):
    try:
        return json.loads(line[len("RESULT "):])
    except (ValueError, KeyError):   # truncated write mid-kill
        return None


def _run_child(mode: str, overall_deadline: float):
    """Run one workload in a child; return (result_dict|None, err_str)."""
    env = dict(os.environ)
    env["_GRAFT_BENCH_CHILD"] = mode
    # persistent XLA compile cache ON by default for every bench child
    # (ROADMAP cold-start item): rounds r03-r05 lost entire workloads
    # to compile deadlines; a warm repo-local cache turns repeat
    # compiles into disk loads, and the per-round compile-time metrics
    # (train_compile_s / *_compile_warmup_s) measure exactly what it
    # saves.  Opt out with PADDLE_TPU_COMPILE_CACHE=0.
    env.setdefault("PADDLE_TPU_COMPILE_CACHE",
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".bench_compile_cache"))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)], env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    lines = []
    lock = threading.Lock()

    def reader():
        for line in proc.stdout:
            with lock:
                lines.append(line.rstrip("\n"))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    t0 = time.time()
    err = ""
    done_at = None
    while True:
        now = time.time()
        with lock:
            init_seen = any(ln.startswith("devices-ok") for ln in lines)
            done = any(ln.startswith("RESULT ") for ln in lines)
        if done and done_at is None:
            done_at = now
        if done and proc.poll() is not None:
            break
        if done_at is not None and now - done_at > 15:
            proc.kill()   # result is in hand; don't wait out a hung teardown
            break
        if not init_seen and now - t0 > INIT_DEADLINE_S:
            err = f"backend init exceeded {INIT_DEADLINE_S}s"
            proc.kill()
            break
        if now - t0 > overall_deadline:
            err = f"bench exceeded {overall_deadline:.0f}s"
            proc.kill()
            break
        if proc.poll() is not None:
            break
        time.sleep(1.0)
    proc.wait()
    t.join(timeout=5)
    result = None
    with lock:
        tail = "\n".join(lines[-15:])
        for ln in lines:
            if ln.startswith("RESULT "):
                result = _parse_result(ln)
    if result is None and not err:
        err = f"child rc={proc.returncode}; tail:\n{tail}"
    return result, err


def main():
    # `python bench.py --fold [1,8,...]`: run ONLY the hapi fold sweep
    # and print its record — the cheap CPU path for tracking the
    # steps/s trend line between full bench rounds
    if "--fold" in sys.argv:
        i = sys.argv.index("--fold")
        if i + 1 < len(sys.argv):
            os.environ["GRAFT_BENCH_HAPI_FOLDS"] = sys.argv[i + 1]
        hapi, herr = _run_child("hapi", 300)
        print(json.dumps(hapi if hapi is not None
                         else {"error": herr[-1000:]}), flush=True)
        return

    # `python bench.py --serving`: run ONLY the serving bench (CPU,
    # cheap) and print its record — the between-rounds tracker for the
    # continuous-batching path, like --fold is for the fit loop
    if "--serving" in sys.argv:
        serving, serr = _run_child("serving", 420)
        print(json.dumps(serving if serving is not None
                         else {"error": serr[-1000:]}), flush=True)
        return

    # `python bench.py --spec`: the speculative-decoding matrix only
    # (ISSUE 19; CPU host-loop proxy, cheap) — tok/s per request and
    # dispatches/token vs the non-speculative engine across
    # k x {self-draft, adversarial-draft}
    if "--spec" in sys.argv:
        spec, sperr = _run_child("spec", 420)
        print(json.dumps(spec if spec is not None
                         else {"error": sperr[-1000:]}), flush=True)
        return

    # `python bench.py --longcontext`: the long-context serving tier
    # (ISSUE 14; CPU, self-contained) — a ~32k-token round through
    # chunked prefill + the fused paged-attention kernel (interpret),
    # prefix-cache hit rate under a shared-system-prompt mix, and the
    # chunked-vs-whole prefill p99 impact on a running decode stream
    if "--longcontext" in sys.argv:
        lc, lcerr = _run_child("longcontext", 600)
        print(json.dumps(lc if lc is not None
                         else {"error": lcerr[-1000:]}), flush=True)
        return

    # `python bench.py --disagg`: the disaggregated prefill/decode
    # tier (ISSUE 16; CPU, self-contained) — running-decode p99
    # inter-token gap while a 32k prompt admits on a SEPARATE prefill
    # replica (vs 88 ms chunked single-engine from PR 14), plus mixed
    # Poisson tok/s through the handoff pipeline vs one engine
    if "--disagg" in sys.argv:
        dg, dgerr = _run_child("disagg", 900)
        print(json.dumps(dg if dg is not None
                         else {"error": dgerr[-1000:]}), flush=True)
        return

    # `python bench.py --fleet`: the distributed observability plane
    # e2e (CPU, cheap) — a real 2-rank launch answered over HTTP:
    # per-rank /metrics, /fleet merge, straggler attribution, ONE
    # merged fleet snapshot attached to the record
    if "--fleet" in sys.argv:
        fleet, flerr = _run_child("fleet", 240)
        print(json.dumps(fleet if fleet is not None
                         else {"error": flerr[-1000:]}), flush=True)
        return

    # `python bench.py --selfheal`: the observability ACTION loop e2e
    # (ISSUE 13; CPU, cheap) — a real 2-rank + spare launch with
    # --drain_stragglers armed and rank 1 stepping 5x slow; records
    # time-from-latency-to-drain and drain-to-recovered-step-time.
    # `--selfheal --hosts 2` (ISSUE 18) runs the multi-host variant:
    # two host agents, whole-node SIGKILL, node-death-to-recovered
    if "--selfheal" in sys.argv:
        hosts = 1
        if "--hosts" in sys.argv:
            i = sys.argv.index("--hosts")
            if i + 1 < len(sys.argv):
                hosts = int(sys.argv[i + 1])
        if hosts >= 2:
            sh, sherr = _run_child("selfheal_hosts", 360)
        else:
            sh, sherr = _run_child("selfheal", 240)
        print(json.dumps(sh if sh is not None
                         else {"error": sherr[-1000:]}), flush=True)
        return

    # `python bench.py --mesh-fold [1,8,...]`: run ONLY the mesh fold
    # sweep (CPU dp mesh, cheap) — the multichip counterpart of --fold
    if "--mesh-fold" in sys.argv:
        i = sys.argv.index("--mesh-fold")
        if i + 1 < len(sys.argv):
            os.environ["GRAFT_BENCH_MESH_FOLDS"] = sys.argv[i + 1]
        mf, merr = _run_child("mesh_fold", 420)
        print(json.dumps(mf if mf is not None
                         else {"error": merr[-1000:]}), flush=True)
        return

    # `python bench.py --pp-fold [1,8,...]`: run ONLY the pipeline
    # fold sweep (CPU pp=2 mesh, cheap) — the pipeline-schedule
    # counterpart of --mesh-fold (ISSUE 15): legacy vs unified fold
    # curve with host-dispatch counts per batch on the record
    if "--pp-fold" in sys.argv:
        i = sys.argv.index("--pp-fold")
        if i + 1 < len(sys.argv):
            os.environ["GRAFT_BENCH_PP_FOLDS"] = sys.argv[i + 1]
        pf, perr = _run_child("pp_fold", 420)
        print(json.dumps(pf if pf is not None
                         else {"error": perr[-1000:]}), flush=True)
        return

    # `python bench.py --dp-compressed`: run ONLY the compressed +
    # sharded dp sweep (CPU dp mesh, cheap) — the dp gradient-path
    # counterpart of --mesh-fold (ISSUE 11)
    if "--dp-compressed" in sys.argv:
        dpc, derr = _run_child("dp_compressed", 420)
        print(json.dumps(dpc if dpc is not None
                         else {"error": derr[-1000:]}), flush=True)
        return

    mode = os.environ.get("_GRAFT_BENCH_CHILD")
    if mode == "gpt":
        return bench_gpt()
    if mode == "resnet":
        return bench_resnet()
    if mode == "ernie":
        return bench_ernie()
    if mode == "flash":
        return bench_flash_micro()
    if mode == "detector":
        return bench_detector()
    if mode == "vit":
        return bench_vit()
    if mode == "hapi":
        return bench_hapi()
    if mode == "mesh_fold":
        return bench_mesh_fold()
    if mode == "pp_fold":
        return bench_pp_fold()
    if mode == "dp_compressed":
        return bench_dp_compressed()
    if mode == "serving":
        return bench_serving()
    if mode == "spec":
        return bench_spec()
    if mode == "longcontext":
        return bench_longcontext()
    if mode == "disagg":
        return bench_disagg()
    if mode == "fleet":
        return bench_fleet()
    if mode == "selfheal":
        return bench_selfheal()
    if mode == "selfheal_hosts":
        return bench_selfheal_hosts()

    t_start = time.time()

    def remaining():
        return GLOBAL_DEADLINE_S - (time.time() - t_start)

    out = {"metric": "gpt2_small_bf16_train_tokens_per_sec_1chip",
           "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0}
    if not os.environ.get("GRAFT_BENCH_FORCE_CPU"):
        out["axon_reachable"] = _probe_axon()
    gpt, err = _run_child("gpt", min(GPT_DEADLINE_S, remaining()))
    if gpt is None and time.time() - t_start < RETRY_ONLY_BEFORE_S:
        # early failure (init-class) — one retry within the global budget
        gpt, err2 = _run_child("gpt", min(GPT_DEADLINE_S, remaining()))
        if gpt is None:
            err = f"attempt1: {err}; attempt2: {err2}"
    if gpt is not None:
        tps = gpt.get("tokens_per_sec", 0.0)
        out["value"] = round(tps, 1)
        out["vs_baseline"] = round(tps / BASELINE_TOKENS_PER_SEC, 3)
        for k in gpt:
            if k != "tokens_per_sec" and (
                    k.startswith("tokens_per_sec_") or k in
                    ("step_ms", "mfu", "model_tflops_per_sec",
                     "flops_per_token_m", "pipeline_overlap_ratio",
                     "train_compile_s")):
                out["gpt_" + k] = gpt[k]
    else:
        out["error"] = err[-2000:]

    # hapi fit loop-overhead microbench: CPU-only by design and cheap
    # (~30s), so it records even when every TPU workload fails — the
    # perf trajectory of the Model.fit hot path stays measurable with
    # the axon tunnel down (ISSUE 4 satellite)
    if remaining() > 60 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        hapi, herr = _run_child("hapi", min(240, remaining()))
        if hapi is not None:
            # the fold sweep's whole record rides along (fold=1 is the
            # PR-4 regression guard, foldK the step-folding trend line)
            out.update(hapi)
        else:
            out["hapi_fit_error"] = herr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["hapi_fit_error"] = "skipped: out of budget"

    # mesh fold sweep: the multichip half of the unified dispatch
    # engine (CPU dp mesh, cheap) — folded mesh steps/s records every
    # round next to the single-chip sweep
    if remaining() > 60 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        mf, mferr = _run_child("mesh_fold", min(240, remaining()))
        if mf is not None:
            out.update(mf)
        else:
            out["mesh_fold_error"] = mferr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["mesh_fold_error"] = "skipped: out of budget"

    # pipeline fold sweep (CPU pp=2 mesh, cheap): legacy vs unified
    # fold curve + host-dispatch counts per batch — the pipeline
    # engine's trend line records every round (ISSUE 15)
    if remaining() > 60 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        pf, pferr = _run_child("pp_fold", min(240, remaining()))
        if pf is not None:
            out.update(pf)
        else:
            out["pp_fold_error"] = pferr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["pp_fold_error"] = "skipped: out of budget"

    # compressed + sharded dp sweep (CPU dp mesh, cheap): wire-format
    # x update-sharding matrix with bytes proxy + opt-state memory —
    # the dp gradient path's trend line records every round (ISSUE 11)
    if remaining() > 60 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        dpc, dperr = _run_child("dp_compressed", min(240, remaining()))
        if dpc is not None:
            out.update(dpc)
        else:
            out["dp_compressed_error"] = dperr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["dp_compressed_error"] = "skipped: out of budget"

    # fleet observability plane e2e (CPU, cheap): a 2-rank launch
    # answered over HTTP — merged fleet snapshot + straggler
    # attribution recorded every round (ISSUE 10)
    if remaining() > 60 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        fleet, flerr = _run_child("fleet", min(240, remaining()))
        if fleet is not None:
            out.update(fleet)
        else:
            out["fleet_error"] = flerr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["fleet_error"] = "skipped: out of budget"

    # serving loop bench: CPU-only by design and cheap, so the
    # continuous-batching path (tokens/s, p99 latency, compile/warmup
    # cold-start) records every round even with the TPU tunnel down
    if remaining() > 90 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        serving, serr = _run_child("serving", min(300, remaining()))
        if serving is not None:
            out.update(serving)
        else:
            out["serving_error"] = serr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["serving_error"] = "skipped: out of budget"

    # speculative decoding tier (CPU, self-contained): tok/s per
    # request and dispatches/token vs the non-speculative engine for
    # k x {self, adversarial} drafts record every round (ISSUE 19)
    if remaining() > 120 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        sp, sperr = _run_child("spec", min(300, remaining()))
        if sp is not None:
            out.update(sp)
        else:
            out["spec_error"] = sperr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["spec_error"] = "skipped: out of budget"

    # long-context serving tier (CPU, self-contained): the 32k-round
    # memory story (kernel vs gather working set), prefix-cache hit
    # rate, and chunked-prefill p99 impact record every round
    if remaining() > 300 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        lc, lcerr = _run_child("longcontext", min(600, remaining()))
        if lc is not None:
            out.update(lc)
        else:
            out["longcontext_error"] = lcerr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["longcontext_error"] = "skipped: out of budget"

    # disaggregated serving tier (CPU, self-contained): running-decode
    # p99 gap under a 32k admission on a separate prefill replica +
    # mixed-Poisson tok/s vs a single engine record every round
    if remaining() > 300 and not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        dg, dgerr = _run_child("disagg", min(900, remaining()))
        if dg is not None:
            out.update(dg)
        else:
            out["disagg_error"] = dgerr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["disagg_error"] = "skipped: out of budget"

    # ResNet-50 gets its slot whenever budget remains — even after a
    # GPT failure (VERDICT r3: images/s never landed in 3 rounds)
    if (remaining() > 120
            and not os.environ.get("GRAFT_BENCH_GPT_ONLY")):
        resnet, rerr = _run_child("resnet", remaining())
        if resnet is not None:
            ips = resnet.get("images_per_sec", 0.0)
            out["resnet50_images_per_sec"] = round(ips, 1)
            out["resnet50_vs_baseline"] = round(
                ips / BASELINE_RESNET50_IMG_PER_SEC, 3)
            for k in ("step_ms", "mfu"):
                if k in resnet:
                    out["resnet50_" + k] = resnet[k]
        else:
            out["resnet50_error"] = rerr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["resnet50_error"] = "skipped: out of budget"
    # ERNIE-3.0 MLM pretrain (north-star names both metrics)
    if (remaining() > 150
            and not os.environ.get("GRAFT_BENCH_GPT_ONLY")):
        ernie, eerr = _run_child("ernie", remaining() - 60)
        if ernie is not None:
            out["ernie3_base_tokens_per_sec"] = round(
                ernie.get("tokens_per_sec", 0.0), 1)
            out["ernie3_base_step_ms"] = ernie.get("step_ms")
        else:
            out["ernie3_base_error"] = eerr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["ernie3_base_error"] = "skipped: out of budget"
    # PP-YOLOE detector (config 5, dynamic-shape buckets) — guarded
    # slot: only when the primary metrics are already in the record
    if (remaining() > 150
            and not os.environ.get("GRAFT_BENCH_GPT_ONLY")):
        det, derr = _run_child("detector", remaining() - 60)
        if det is not None:
            out["ppyoloe_s_images_per_sec"] = round(
                det.get("images_per_sec", 0.0), 1)
            out["ppyoloe_s_step_ms"] = det.get("step_ms")
            out["ppyoloe_s_buckets"] = det.get("buckets")
        else:
            out["ppyoloe_s_error"] = derr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["ppyoloe_s_error"] = "skipped: out of budget"
    if (gpt is not None and remaining() > 90
            and not os.environ.get("GRAFT_BENCH_GPT_ONLY")):
        flash, ferr = _run_child("flash", remaining())
        if flash is not None:
            out.update(flash)
        else:
            out["flash_microbench_error"] = ferr[-500:]
    elif not os.environ.get("GRAFT_BENCH_GPT_ONLY"):
        out["flash_microbench_skipped"] = (
            "gpt bench failed" if gpt is None else "out of budget")
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
