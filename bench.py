"""Benchmark: GPT-2-small causal-LM training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: the ERNIE/GPT class of baseline configs (BASELINE.json:9-10)
reduced to one chip — bf16 train step (fwd+bwd+AdamW) of a 124M-param
GPT-2-small at batch 8 × seq 1024, compiled to a single XLA program.

vs_baseline: BASELINE.md records no published reference numbers
("published": {} — empty reference mount), so the denominator is the
community-typical per-A100 figure for GPT-2-small-class training used
as the provisional bar: 25k tokens/s/GPU.  Replace when real reference
numbers exist.
"""

import json
import time

import numpy as np

BASELINE_TOKENS_PER_SEC = 25_000.0


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer, amp
    from paddle_tpu.distributed import collective
    from paddle_tpu.distributed.runner import DistributedRunner
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                    num_hidden_layers=12, num_attention_heads=12,
                    intermediate_size=3072,
                    max_position_embeddings=1024,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=True)
    batch, seq = 8, 1024
    net = GPTForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=net.parameters(),
                          multi_precision=True)
    # O2: bf16 params + fp32 master weights in the optimizer
    amp.decorate(net, opt, level="O2", dtype="bfloat16")
    mesh = collective.build_mesh({})
    collective.set_mesh(mesh)
    runner = DistributedRunner(net, opt, GPTPretrainingCriterion(),
                               mesh=mesh)

    rng = np.random.RandomState(0)
    x = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    y = np.roll(x, -1, axis=1)

    # compile + warmup (float() forces a full device sync)
    float(runner.train_step([x], [y]))
    float(runner.train_step([x], [y]))

    steps = 10
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = runner.train_step([x], [y])
    jax.block_until_ready(runner._opt_state)
    float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    print(json.dumps({
        "metric": "gpt2_small_bf16_train_tokens_per_sec_1chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
