"""paddle.metric parity (python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from ..tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        order = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1) if \
                label_np.shape[-1] == 1 else label_np.argmax(-1)
        correct = (order == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        num = c.reshape(-1, self.maxk).shape[0]
        accs = []
        for k in self.topk:
            corr_k = c.reshape(-1, self.maxk)[:, :k].sum()
            self.total[self.topk.index(k)] += corr_k
            self.count[self.topk.index(k)] += num
            accs.append(corr_k / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p.reshape(-1) * self.num_thresholds).astype(int),
                       0, self.num_thresholds)
        for b, lab in zip(bins, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds descending
        area = 0.0
        pos = neg = 0.0
        prev_pos = prev_neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            pos += self._stat_pos[i]
            neg += self._stat_neg[i]
            area += (neg - prev_neg) * (pos + prev_pos) / 2.0
            prev_pos, prev_neg = pos, neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    corr = (order == lab[:, None]).any(axis=1).mean()
    return Tensor(np.asarray(corr, dtype=np.float32))
