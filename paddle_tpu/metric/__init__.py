"""paddle.metric parity (python/paddle/metric/metrics.py).

Two update paths (DESIGN-PERF.md): the classic numpy ``compute`` /
``update`` pair (host-side, used for direct calls and metrics without a
device kernel) and, for metrics flagged ``supports_device_update``, a
device fast path the ``Model.fit`` hot loop uses.

Device protocol (step-folding aware):

- ``device_batch_stats()`` returns a pure ``(pred, label) → stat``
  function that traces INTO the compiled train/eval step.  The stat is
  **self-contained** (it embeds any row/bin counts it needs), so stats
  are combinable by plain addition — which is exactly what the folded
  ``lax.scan`` carry does.
- ``device_acc_init()`` returns the zero accumulator.  Under step
  folding the accumulator rides the donated scan carry across steps
  AND across dispatches; ``adopt_device_acc`` hands the metric the
  latest carry value (a reference — no sync).
- ``update_device_stats(stat)`` is the single-step path: one host list
  append per step, materialized together at ``accumulate()``.
- ``device_step_result(stack, i)`` builds the per-logical-step log
  value from a folded dispatch's stacked ``[K, ...]`` stats — a
  ``LazyScalar`` view over the shared ``LazyStack``, so per-step logs
  cost one transfer per dispatch group, and only when formatted.

``accumulate()`` merges the host counters, the pending single-step
stats, and the device accumulator — the ONE epoch-boundary sync.
"""

from __future__ import annotations

import numpy as np

from ..tensor import Tensor
from ..framework.lazy import LazyScalar


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    # metrics that implement the device-stat protocol set this True;
    # Model.fit then keeps their accumulators device-resident
    supports_device_update = False

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args

    # -- device-stat protocol (defaults for scalar-result metrics) -----
    def device_batch_stats(self):
        raise NotImplementedError

    def device_acc_init(self):
        raise NotImplementedError

    def _stat_result(self, stat):
        """Host finisher: one batch's (or slice's) stat → metric value.
        Runs inside LazyScalar materialization — keep it numpy-cheap."""
        raise NotImplementedError

    def update_device_stats(self, stat):
        """Single-step path: adopt one batch's device-side stat vector
        — a host list append, no sync.  Totals materialize in
        accumulate() at the epoch boundary."""
        self._dev_pending.append(stat)
        return LazyScalar(stat, post=self._stat_result)

    def device_step_result(self, stack, i):
        """Folded path: the per-logical-step log value, an index-sliced
        view over the dispatch group's shared LazyStack."""
        return LazyScalar(stack, post=lambda a, i=i: self._stat_result(a[i]))

    def adopt_device_acc(self, acc):
        """Folded path: adopt the scan carry's running accumulator (a
        device reference — accumulation already happened in-program)."""
        self._dev_acc = acc

    def update_device(self, pred, label):
        """Standalone device update (runner/eager eval paths): one
        small jitted reduction, accumulators stay on device until
        accumulate()."""
        if getattr(self, "_stats_fn", None) is None:
            import jax
            self._stats_fn = jax.jit(self.device_batch_stats())
        return self.update_device_stats(self._stats_fn(pred, label))

    def _device_stat_sum(self):
        """Epoch-boundary materialization of pending single-step stats
        plus the folded-carry accumulator; None when no device updates
        happened.  The host merge sums in float64, so only the in-carry
        float32 addition bounds exactness — counts stay exact below
        2**24 rows per epoch (documented in DESIGN-PERF.md; beyond
        that, ``steps_per_dispatch=0`` keeps per-batch granularity)."""
        stats = [np.asarray(v) for v in getattr(self, "_dev_pending", [])]
        acc = getattr(self, "_dev_acc", None)
        if acc is not None:
            stats.append(np.asarray(acc))
        if not stats:
            return None
        return np.sum(np.stack(stats), axis=0, dtype=np.float64)

    def _reset_device_state(self):
        self._dev_pending = []
        self._dev_acc = None


class Accuracy(Metric):
    supports_device_update = True

    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self._stats_fn = None
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        order = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1) if \
                label_np.shape[-1] == 1 else label_np.argmax(-1)
        correct = (order == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        num = c.reshape(-1, self.maxk).shape[0]
        accs = []
        for k in self.topk:
            corr_k = c.reshape(-1, self.maxk)[:, :k].sum()
            self.total[self.topk.index(k)] += corr_k
            self.count[self.topk.index(k)] += num
            accs.append(corr_k / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    # -- device-resident fast path (Model.fit hot loop) ----------------
    def device_batch_stats(self):
        """Pure (pred, label) → stat vector, traceable INSIDE the
        compiled train step.  The vector is [corr_k1, ..., corr_kn,
        rows]: the trailing row count makes the stat self-contained so
        the folded scan carry accumulates it by plain addition."""
        import jax
        import jax.numpy as jnp
        maxk, topk = self.maxk, self.topk

        def stats(pred, label):
            _, order = jax.lax.top_k(pred, maxk)
            if label.ndim == pred.ndim:
                label = (label[..., 0] if label.shape[-1] == 1
                         else label.argmax(-1))
            correct = (order == label[..., None]).astype(jnp.float32)
            flat = correct.reshape(-1, maxk)
            counts = [flat[:, :k].sum() for k in topk]
            counts.append(jnp.asarray(flat.shape[0], jnp.float32))
            return jnp.stack(counts)

        return stats

    def device_acc_init(self):
        import jax.numpy as jnp
        return jnp.zeros(len(self.topk) + 1, jnp.float32)

    def _result_views(self, dev, pick):
        if len(self.topk) == 1:
            return LazyScalar(
                dev, post=lambda a: (lambda c: float(c[0])
                                     / max(float(c[-1]), 1.0))(pick(a)))
        return [LazyScalar(
            dev, post=lambda a, j=j: (lambda c: float(c[j])
                                      / max(float(c[-1]), 1.0))(pick(a)))
            for j in range(len(self.topk))]

    def update_device_stats(self, stat):
        self._dev_pending.append(stat)
        return self._result_views(stat, lambda a: a)

    def device_step_result(self, stack, i):
        return self._result_views(stack, lambda a, i=i: a[i])

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)
        self._reset_device_state()

    def accumulate(self):
        total = list(self.total)
        count = list(self.count)
        dev = self._device_stat_sum()
        if dev is not None:
            # epoch-boundary materialization of the device accumulators
            for i in range(len(self.topk)):
                total[i] += float(dev[i])
                count[i] += float(dev[-1])
        res = [t / max(c, 1) for t, c in zip(total, count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    supports_device_update = True

    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def device_batch_stats(self):
        """Stat vector [tp, fp] — bit-exact counts (small integers in
        float32), so device and host accumulation agree exactly."""
        import jax.numpy as jnp

        def stats(pred, label):
            p = pred.reshape(-1) > 0.5
            l = label.reshape(-1).astype(jnp.int32)
            tp = jnp.sum((p & (l == 1)).astype(jnp.float32))
            fp = jnp.sum((p & (l == 0)).astype(jnp.float32))
            return jnp.stack([tp, fp])

        return stats

    def device_acc_init(self):
        import jax.numpy as jnp
        return jnp.zeros(2, jnp.float32)

    def _stat_result(self, stat):
        denom = float(stat[0]) + float(stat[1])
        return float(stat[0]) / denom if denom else 0.0

    def reset(self):
        self.tp = 0
        self.fp = 0
        self._reset_device_state()

    def accumulate(self):
        tp, fp = float(self.tp), float(self.fp)
        dev = self._device_stat_sum()
        if dev is not None:
            tp += float(dev[0])
            fp += float(dev[1])
        denom = tp + fp
        return tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    supports_device_update = True

    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def device_batch_stats(self):
        import jax.numpy as jnp

        def stats(pred, label):
            p = pred.reshape(-1) > 0.5
            l = label.reshape(-1).astype(jnp.int32)
            tp = jnp.sum((p & (l == 1)).astype(jnp.float32))
            fn = jnp.sum((~p & (l == 1)).astype(jnp.float32))
            return jnp.stack([tp, fn])

        return stats

    def device_acc_init(self):
        import jax.numpy as jnp
        return jnp.zeros(2, jnp.float32)

    def _stat_result(self, stat):
        denom = float(stat[0]) + float(stat[1])
        return float(stat[0]) / denom if denom else 0.0

    def reset(self):
        self.tp = 0
        self.fn = 0
        self._reset_device_state()

    def accumulate(self):
        tp, fn = float(self.tp), float(self.fn)
        dev = self._device_stat_sum()
        if dev is not None:
            tp += float(dev[0])
            fn += float(dev[1])
        denom = tp + fn
        return tp / denom if denom else 0.0

    def name(self):
        return self._name


def _auc_from_hist(pos, neg):
    """Vectorized trapezoid over descending thresholds — same area the
    accumulate() loop computes, used for per-batch log values."""
    tp = np.cumsum(pos[::-1])
    fp = np.cumsum(neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    if tot_pos == 0 or tot_neg == 0:
        return 0.0
    prev_tp = np.concatenate([[0.0], tp[:-1]])
    prev_fp = np.concatenate([[0.0], fp[:-1]])
    area = float(np.sum((fp - prev_fp) * (tp + prev_tp) / 2.0))
    return area / float(tot_pos * tot_neg)


class Auc(Metric):
    supports_device_update = True

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p.reshape(-1) * self.num_thresholds).astype(int),
                       0, self.num_thresholds)
        for b, lab in zip(bins, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def device_batch_stats(self):
        """Stat [2, num_thresholds+1]: positive/negative histogram rows
        built with one in-step scatter-add each — the bins ride the
        folded carry like Accuracy's counts do."""
        import jax.numpy as jnp
        T = self.num_thresholds

        def stats(pred, label):
            p = pred
            if p.ndim == 2 and p.shape[1] == 2:
                p = p[:, 1]
            p = p.reshape(-1)
            lab = (label.reshape(-1) != 0).astype(jnp.float32)
            bins = jnp.clip((p * T).astype(jnp.int32), 0, T)
            pos = jnp.zeros(T + 1, jnp.float32).at[bins].add(lab)
            neg = jnp.zeros(T + 1, jnp.float32).at[bins].add(1.0 - lab)
            return jnp.stack([pos, neg])

        return stats

    def device_acc_init(self):
        import jax.numpy as jnp
        return jnp.zeros((2, self.num_thresholds + 1), jnp.float32)

    def _stat_result(self, stat):
        return _auc_from_hist(stat[0], stat[1])

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)
        self._reset_device_state()

    def accumulate(self):
        stat_pos = self._stat_pos
        stat_neg = self._stat_neg
        dev = self._device_stat_sum()
        if dev is not None:
            stat_pos = stat_pos + dev[0]
            stat_neg = stat_neg + dev[1]
        return _auc_from_hist(stat_pos, stat_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    corr = (order == lab[:, None]).any(axis=1).mean()
    return Tensor(np.asarray(corr, dtype=np.float32))
