"""paddle.metric parity (python/paddle/metric/metrics.py).

Two update paths (DESIGN-PERF.md): the classic numpy ``compute`` /
``update`` pair (host-side, used for direct calls and metrics without a
device kernel) and, for metrics flagged ``supports_device_update``, a
``update_device(pred, label)`` fast path the ``Model.fit`` hot loop
uses — a small jitted reduction whose correct/total accumulators stay
ON DEVICE until ``accumulate()`` materializes them at the epoch
boundary.  The hot loop never pulls predictions to the host.
"""

from __future__ import annotations

import numpy as np

from ..tensor import Tensor
from ..framework.lazy import LazyScalar


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    # metrics that implement update_device(pred, label) set this True;
    # Model.fit then keeps their accumulators device-resident
    supports_device_update = False

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    supports_device_update = True

    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self._stats_fn = None
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        order = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1) if \
                label_np.shape[-1] == 1 else label_np.argmax(-1)
        correct = (order == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        num = c.reshape(-1, self.maxk).shape[0]
        accs = []
        for k in self.topk:
            corr_k = c.reshape(-1, self.maxk)[:, :k].sum()
            self.total[self.topk.index(k)] += corr_k
            self.count[self.topk.index(k)] += num
            accs.append(corr_k / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    # -- device-resident fast path (Model.fit hot loop) ----------------
    def device_batch_stats(self):
        """Pure (pred, label) → stat vector, traceable INSIDE the
        compiled train step — the per-batch top-k correct counts ride
        the step's XLA program, so the hot loop dispatches zero extra
        device ops for metrics."""
        import jax
        import jax.numpy as jnp
        maxk, topk = self.maxk, self.topk

        def stats(pred, label):
            _, order = jax.lax.top_k(pred, maxk)
            if label.ndim == pred.ndim:
                label = (label[..., 0] if label.shape[-1] == 1
                         else label.argmax(-1))
            correct = (order == label[..., None]).astype(jnp.float32)
            flat = correct.reshape(-1, maxk)
            return jnp.stack([flat[:, :k].sum() for k in topk])

        return stats

    def update_device_stats(self, stat_vec, rows):
        """Adopt one batch's device-side stat vector: a host list
        append — no add dispatch, no sync.  Totals materialize in
        accumulate() at the epoch boundary."""
        self._dev_pending.append(stat_vec)
        self._dev_rows += rows
        if len(self.topk) == 1:
            return LazyScalar(stat_vec,
                              lambda c, n=rows: float(c[0]) / max(n, 1))
        return [LazyScalar(stat_vec,
                           lambda c, i=i, n=rows: float(c[i]) / max(n, 1))
                for i in range(len(self.topk))]

    def update_device(self, pred, label):
        """Standalone device update (eval path): one small jitted
        reduction, accumulators stay on device until accumulate()."""
        if self._stats_fn is None:
            import jax
            self._stats_fn = jax.jit(self.device_batch_stats())
        rows = 1
        for s in pred.shape[:-1]:
            rows *= int(s)
        return self.update_device_stats(self._stats_fn(pred, label), rows)

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)
        self._dev_pending = []
        self._dev_rows = 0

    def accumulate(self):
        total = list(self.total)
        count = list(self.count)
        if self._dev_pending:
            # epoch-boundary materialization of the device accumulators
            corr = np.sum(np.asarray(self._dev_pending), axis=0)
            for i in range(len(self.topk)):
                total[i] += float(corr[i])
                count[i] += self._dev_rows
        res = [t / max(c, 1) for t, c in zip(total, count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p.reshape(-1) * self.num_thresholds).astype(int),
                       0, self.num_thresholds)
        for b, lab in zip(bins, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds descending
        area = 0.0
        pos = neg = 0.0
        prev_pos = prev_neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            pos += self._stat_pos[i]
            neg += self._stat_neg[i]
            area += (neg - prev_neg) * (pos + prev_pos) / 2.0
            prev_pos, prev_neg = pos, neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    corr = (order == lab[:, None]).any(axis=1).mean()
    return Tensor(np.asarray(corr, dtype=np.float32))
