"""paddle.sysconfig (parity: upstream ``python/paddle/sysconfig.py``):
header/library paths for building extensions against the framework.

The TPU-native framework is pure Python over jax — there are no
framework C headers to compile against; get_include()/get_lib() return
the package paths (existing dirs) so build scripts that merely join
paths keep working, and native/ carries the in-repo C++ sources.
"""

import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    path = os.path.join(_PKG, "include")
    return path if os.path.isdir(path) else _PKG


def get_lib() -> str:
    path = os.path.join(_PKG, "libs")
    return path if os.path.isdir(path) else _PKG
