"""Place / device abstraction.

Paddle identifies where a tensor lives with ``Place`` objects
(upstream: paddle/phi/common/place.h — CPUPlace/GPUPlace/XPUPlace/
CustomPlace).  On the TPU build a Place is a thin handle over a
``jax.Device``: ``TPUPlace(i)`` ↦ i-th accelerator device,
``CPUPlace()`` ↦ host.  ``paddle.set_device("tpu:0")`` selects the
default placement used by creation ops; XLA owns streams and memory so a
DeviceContext equivalent is unnecessary (SURVEY.md §2.1 DeviceContext
row).
"""

from __future__ import annotations

from typing import Optional, Union

import jax


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._device_id == other._device_id)

    def __hash__(self):
        return hash((type(self).__name__, self._device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self._device_id})"

    def jax_device(self) -> Optional[jax.Device]:
        """Resolve to a jax.Device (None = let jax use its default)."""
        kind = self.device_type
        if kind == "cpu":
            return jax.devices("cpu")[0]
        devs = jax.local_devices()
        accel = [d for d in devs if d.platform != "cpu"] or devs
        return accel[self._device_id % len(accel)]


class CPUPlace(Place):
    device_type = "cpu"

    def __repr__(self):
        return "Place(cpu)"


class TPUPlace(Place):
    device_type = "tpu"


# Compat aliases: scripts written for GPU Paddle say CUDAPlace/gpu — map
# them onto the accelerator present (TPU here).
class CUDAPlace(TPUPlace):
    device_type = "gpu"


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    device_type = "xpu"


class CustomPlace(TPUPlace):
    def __init__(self, dev_type: str = "tpu", device_id: int = 0):
        super().__init__(device_id)
        self.device_type = dev_type


_current_place: Place = None  # resolved lazily


def _accelerator_present() -> bool:
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


def _default_place() -> Place:
    return TPUPlace(0) if _accelerator_present() else CPUPlace()


def get_device() -> str:
    p = _expected_place()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"{p.device_type}:{p.get_device_id()}"


def set_device(device: Union[str, Place]) -> Place:
    """``paddle.set_device('tpu'|'gpu:0'|'cpu')``.  'gpu'/'cuda'/'xpu' are
    accepted and mapped to the accelerator actually present."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    dev = device.lower()
    if ":" in dev:
        kind, idx = dev.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = dev, 0
    if kind == "cpu":
        _current_place = CPUPlace()
    elif kind in ("tpu", "gpu", "cuda", "xpu", "npu"):
        _current_place = TPUPlace(idx)
    else:
        _current_place = CustomPlace(kind, idx)
    return _current_place


def _expected_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = _default_place()
    return _current_place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    return len(accel) or len(jax.devices())
