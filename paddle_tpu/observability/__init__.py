"""paddle_tpu.observability — unified tracing, metrics, export
(DESIGN-OBSERVABILITY.md).

One subsystem answers "where did this step/request spend its time" on
a live system:

- :mod:`.trace`   — low-overhead span recorder (monotonic-clock ring
  buffer, thread-aware, ~zero cost when disabled; arm with
  ``PADDLE_TPU_TRACE=1`` or ``trace.enable()``); exports
  Chrome/Perfetto ``trace_event`` JSON and a compact summary.
- :mod:`.metrics` — process-wide registry of counters/gauges/
  histograms whose hot-path instruments accept lazy device scalars
  and defer the device→host sync to scrape time.
- :mod:`.export`  — JSON snapshot + Prometheus text dump.

Quickstart::

    import paddle_tpu as paddle
    paddle.observability.trace.enable()       # or PADDLE_TPU_TRACE=1
    model.fit(...)                            # spans record as it runs
    paddle.observability.trace.dump_chrome_trace("fit_trace.json")
    print(paddle.observability.scrape())      # all metrics, one dict

The training/serving hot loops are instrumented unconditionally —
dispatch spans, auto-K gauges, request lifecycle spans, checkpoint IO
— but record nothing until armed; step/dispatch wall-time histograms
and counters are ALWAYS on (host floats, no device syncs).
"""

from __future__ import annotations

from ..framework import env_knobs as _env_knobs
from . import trace  # noqa: F401
from . import metrics  # noqa: F401
from . import export  # noqa: F401
from . import events  # noqa: F401
from . import aggregate  # noqa: F401
from . import http  # noqa: F401
from .metrics import registry  # noqa: F401

__all__ = ["trace", "metrics", "export", "events", "aggregate",
           "http", "registry", "scrape", "scrape_prometheus"]


def scrape(materialize: bool = True):
    """ONE dict over every metric in the process-wide registry —
    dispatch, fit, mesh, serving, checkpoint.  ``materialize=True``
    pays the deferred device→host syncs of lazy-valued metrics here
    (the sanctioned sync point); the instrumented loops never sync."""
    return export.snapshot(materialize=materialize)


def scrape_prometheus() -> str:
    """The registry in Prometheus text exposition format."""
    return export.to_prometheus_text()


# PADDLE_TPU_TRACE=1 arms the span recorder at import — i.e. before
# any instrumented module dispatches — so "trace this run" is an env
# var, not a code change.  Capacity knob: PADDLE_TPU_TRACE_CAPACITY.
if _env_knobs.get_bool("PADDLE_TPU_TRACE"):
    # malformed capacity must not kill the import (get_int -> default)
    _cap = _env_knobs.get_int("PADDLE_TPU_TRACE_CAPACITY", 0)
    # nonpositive values (unset, 0, or e.g. -1) keep the default ring
    trace.enable(capacity=_cap if _cap > 0 else None)
    del _cap

# PADDLE_TPU_METRICS_PORT=<base> arms the per-process HTTP scrape
# endpoint the same way (DESIGN-OBSERVABILITY.md §Distributed plane):
# rank r serves base+1+r, a rank-less process serves base.  Unset/0
# creates NOTHING — no socket, no thread (zero-overhead contract,
# pinned in tests).  Parked spares arm at promotion instead
# (http.serve_for_rank).
http.maybe_serve_from_env()
