"""Control-loop decision ring (DESIGN-OBSERVABILITY.md §Action loop).

PR 13 turns the observability plane into a control plane: the launch
controller drains stragglers, the serving router scales replicas and
sheds admissions.  Counters say *how often* the loop acted; this ring
says *what it decided and why*, decision by decision, so an operator
can audit the loop after the fact:

    >>> paddle.observability.events.record("drain", rank=1,
    ...                                    step_time_s=1.62)
    >>> paddle.observability.events.snapshot()
    [{"ts": 1754300000.123, "kind": "drain", "rank": 1,
      "step_time_s": 1.62}]

Semantics:

- **Bounded.**  A ``deque(maxlen=capacity)`` (default 256, knob
  ``PADDLE_TPU_EVENTS_CAPACITY``): a chatty loop evicts its own oldest
  decisions, never grows the process.  Record rate is bounded by
  decision rate by construction — callers record *decisions*
  (drain/scale/shed-state transitions), not per-request outcomes
  (those are counters).
- **Host-only.**  ``record`` stamps wall-clock ``time.time()`` and
  stores plain dicts; nothing here can touch the device, so the ring
  is scrapable mid-wedge exactly like ``/healthz``.
- **Always on.**  Unlike tracing there is no arming knob: the ring is
  a tiny fixed cost paid only when a control loop actually decides
  something, and a self-driving fleet with an un-auditable action log
  is worse than none.

Exposure: every per-process HTTP endpoint serves the ring at
``/events``; the launch controller's ``/fleet/events`` merges its own
ring with every live member's, each entry tagged with its ``source``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..framework import env_knobs

__all__ = ["record", "snapshot", "capacity", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 256


def _env_capacity() -> int:
    # malformed knob must not kill the import (get_int -> default)
    cap = env_knobs.get_int("PADDLE_TPU_EVENTS_CAPACITY", 0)
    return cap if cap > 0 else DEFAULT_CAPACITY


_lock = threading.Lock()
_ring: deque = deque(maxlen=_env_capacity())


def record(kind: str, **detail: Any) -> Dict[str, Any]:
    """Append one control-loop decision: ``kind`` (``drain``,
    ``scale_up``, ``shed_on`` …) plus whatever context the decision
    was made on.  ``detail`` values should be host scalars/strings —
    they go straight to JSON on ``/events``.  Returns the stored
    entry (with its timestamp) so callers can log it too."""
    entry = {"ts": time.time(), "kind": str(kind), **detail}
    with _lock:
        _ring.append(entry)
    return entry


def snapshot() -> List[Dict[str, Any]]:
    """The ring oldest-first (copies — callers can't mutate the
    ring)."""
    with _lock:
        return [dict(e) for e in _ring]


def capacity() -> int:
    return _ring.maxlen or DEFAULT_CAPACITY


def _reset_for_tests(capacity: Optional[int] = None):
    """Clear the ring (and optionally resize it) — test isolation."""
    global _ring
    with _lock:
        _ring = deque(maxlen=capacity or _env_capacity())
