"""Process-wide metrics registry (DESIGN-OBSERVABILITY.md).

Counters, gauges and fixed-bucket histograms with Prometheus-shaped
semantics, shared by every subsystem — dispatch engine, fit loop,
mesh runner, serving engine, checkpoint IO — so one
``observability.scrape()`` answers for the whole process.

The hot-path contract (the same one ``scripts/check_host_sync.py``
enforces on the loops these instruments live in):

- **Instruments accept ``LazyScalar``-like device values.**  A value
  that is not a plain ``int``/``float``/``bool`` is held as-is and
  materialized at *scrape* time — the device→host sync rides the
  existing ``LazyScalar._materialize`` whitelisted path, never the
  training/serving loop.  Pending lazies are bounded
  (``_MAX_PENDING``): past the bound the oldest are dropped with a
  drop counter, because a registry nobody scrapes must not grow
  without bound.
- **Gauges can be function-backed** (:meth:`Gauge.set_function`):
  the callable runs at scrape time only, so "queue depth" and
  "KV-pool fragmentation" cost the serving loop literally nothing.
- **Locks are per-instrument and held for nanoseconds** (an int add,
  a bisect) — no instrument ever blocks on device work.

Naming convention: ``<subsystem>_<quantity>_<unit>[_total]`` —
``dispatch_steps_total``, ``serving_latency_s``,
``checkpoint_save_s``.  Labels are a frozen kv-set fixed at
instrument creation (e.g. one ``engine="e0"`` child per serving
engine); the registry keys children by (name, labels).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "DEFAULT_TIME_BUCKETS"]

# Default latency bucket edges (seconds): 100us .. ~2min, roughly
# log-spaced.  Chosen once so every duration histogram in the process
# aggregates and compares on the same grid.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

_MAX_PENDING = 4096


def _is_host_number(v) -> bool:
    # np.number covers np.float32/np.int64 etc. — host-cheap scalars
    # that are NOT int/float subclasses and must not be deferred as
    # "lazy device values" (deferred values can be evicted unscraped)
    return isinstance(v, (int, float, bool, np.number))


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping for label VALUES — an unescaped
    quote/backslash/newline in one label corrupts the whole payload."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _materialize(v) -> float:
    """Deferred-value finisher, called at scrape time only: a lazy
    device scalar (``LazyScalar``, jax array, anything float()-able)
    pays its device→host sync HERE, never on the instrumented loop."""
    return float(v)


class _Instrument:
    __slots__ = ("name", "help", "labels", "_lock", "_pending",
                 "pending_dropped", "materialize_errors")

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.help = help
        self.labels = labels
        self._lock = threading.Lock()
        # deferred (lazy device) values, materialized at scrape
        self._pending: List[Any] = []
        self.pending_dropped = 0
        self.materialize_errors = 0

    def _push_pending(self, v):
        with self._lock:
            if len(self._pending) >= _MAX_PENDING:
                self._pending.pop(0)
                self.pending_dropped += 1
            self._pending.append(v)

    def _drain_pending(self) -> List[Any]:
        with self._lock:
            out, self._pending = self._pending, []
        return out

    def _materialize_safe(self, v) -> Optional[float]:
        """Guarded ``float(v)``: a lazy value whose device computation
        FAILED (async XLA error surfacing at device_get) must not take
        down every scrape, nor discard the other drained observations
        — count it and move on."""
        try:
            return _materialize(v)
        except Exception:
            self.materialize_errors += 1
            return None

    def labels_suffix(self) -> str:
        if not self.labels:
            return ""
        inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                         for k, v in self.labels)
        return "{" + inner + "}"

    def key(self) -> str:
        return self.name + self.labels_suffix()


class Counter(_Instrument):
    """Monotonically increasing count.  ``inc`` with a host number is
    an add under a lock; a lazy device value is deferred to scrape."""

    __slots__ = ("_value",)

    kind = "counter"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value = 0.0

    def inc(self, n=1):
        if _is_host_number(n):
            with self._lock:
                self._value += n
        else:
            self._push_pending(n)

    def collect(self, materialize: bool = True) -> float:
        if materialize:
            for v in self._drain_pending():
                m = self._materialize_safe(v)  # sync OUTSIDE the lock
                if m is None:
                    continue
                with self._lock:
                    self._value += m
        with self._lock:
            return self._value


class Gauge(_Instrument):
    """Last-write-wins sample.  ``set`` stores host numbers AND lazy
    device values as-is (the device read happens at scrape);
    ``set_function`` makes the gauge collect-time-computed — zero
    hot-path cost, always fresh."""

    __slots__ = ("_value", "_fn")

    kind = "gauge"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value: Any = None
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            base = self._value if _is_host_number(self._value) else 0.0
            self._value = base + n

    def set_function(self, fn: Callable[[], float]):
        """Collect-time-computed gauge.  ``fn`` must read HOST state
        only (it is skipped under ``materialize=False``, the mode the
        watchdog's hung-process dump relies on); return None to
        scrape as absent."""
        with self._lock:
            self._fn = fn

    def collect(self, materialize: bool = True) -> Optional[float]:
        with self._lock:
            fn, v = self._fn, self._value
        if fn is not None:
            if not materialize:
                # host-only mode must not run arbitrary callables —
                # the watchdog dumps from a hung process
                return None
            try:
                val = fn()
                # weakref-backed fns return None once their owner is
                # dead: absent, not a NaN-forever series
                return None if val is None else float(val)
            except Exception:
                return None
        if v is None:
            return None
        if _is_host_number(v):
            return float(v)
        if not materialize:
            return None
        m = self._materialize_safe(v)
        if m is None:                 # failed lazy: scrape as absent
            return None
        with self._lock:
            # cache the materialized value only if no newer write won
            if self._value is v:
                self._value = m
        return m


class Histogram(_Instrument):
    """Fixed-bucket-edge histogram: ``observe`` of a host number is a
    bisect + two adds under a lock; a lazy device value defers its
    bucketing to scrape.  Export is Prometheus-shaped (cumulative
    ``le`` buckets incl. ``+Inf``, plus sum and count);
    :meth:`quantile` interpolates within the landing bucket, which is
    how the serving stats adapter keeps its p50/p99 shape."""

    __slots__ = ("edges", "_counts", "_sum", "_count")

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Tuple[Tuple[str, str], ...] = (),
                 edges: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, help, labels)
        es = tuple(float(e) for e in edges)
        if not es or any(b <= a for a, b in zip(es, es[1:])):
            raise ValueError("histogram edges must be strictly "
                             f"increasing and non-empty: {es}")
        self.edges = es
        self._counts = [0] * (len(es) + 1)      # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        if not _is_host_number(v):
            self._push_pending(v)
            return
        i = bisect.bisect_left(self.edges, v)   # v <= edges[i] lands i
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def _flush(self):
        for v in self._drain_pending():
            m = self._materialize_safe(v)
            if m is not None:
                self.observe(m)

    def collect(self, materialize: bool = True) -> Dict[str, Any]:
        if materialize:
            self._flush()
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return {"buckets": [[e, c] for e, c in zip(
                    (*self.edges, math.inf), cum)],
                "sum": total, "count": n}

    def quantile(self, q: float, materialize: bool = True) -> float:
        """Estimated q-quantile (q in [0,1]) with linear interpolation
        inside the landing bucket; 0.0 when empty.  Monotone in q by
        construction.  The +Inf bucket clamps to the top edge."""
        if materialize:
            self._flush()
        with self._lock:
            counts = list(self._counts)
            n = self._count
        if n == 0:
            return 0.0
        rank = q * n
        acc = 0
        for i, c in enumerate(counts):
            if acc + c >= rank and c > 0:
                lo = 0.0 if i == 0 else self.edges[i - 1]
                hi = (self.edges[i] if i < len(self.edges)
                      else self.edges[-1])
                frac = (rank - acc) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            acc += c
        return float(self.edges[-1])


class MetricsRegistry:
    """Get-or-create instrument registry keyed by (name, labels).
    Same name + labels returns the SAME instrument (so module-level
    and per-engine call sites converge); same name with a different
    kind raises — a name means one thing process-wide."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, Tuple], _Instrument] = {}

    @staticmethod
    def _label_key(labels: Optional[Dict[str, str]]):
        if not labels:
            return ()
        return tuple(sorted((str(k), str(v))
                            for k, v in labels.items()))

    def _get_or_create(self, cls, name, help, labels, edges=None):
        lk = self._label_key(labels)
        with self._lock:
            inst = self._instruments.get((name, lk))
            if inst is None:
                kw = {} if edges is None else {"edges": edges}
                inst = cls(name, help=help, labels=lk, **kw)
                self._instruments[(name, lk)] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{inst.kind}, requested {cls.kind}")
            elif (edges is not None
                  and tuple(float(e) for e in edges) != inst.edges):
                # silently returning the first-created edges would
                # bucket the second site's observations nonsensically
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    f"edges {inst.edges}, requested "
                    f"{tuple(float(e) for e in edges)}")
            return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  edges: Optional[Sequence[float]] = None) -> Histogram:
        """``edges=None`` means "default buckets if creating, accept
        whatever an existing instrument has"; EXPLICIT edges that
        conflict with an existing instrument raise ValueError."""
        return self._get_or_create(Histogram, name, help, labels,
                                   edges=edges)

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._instruments.values())

    def unregister(self, name: str,
                   labels: Optional[Dict[str, str]] = None) -> bool:
        """Drop one instrument (e.g. a retired engine's labeled
        child).  Cached references keep recording into the orphan;
        it just stops appearing in scrapes.  Returns True if found."""
        with self._lock:
            return self._instruments.pop(
                (name, self._label_key(labels)), None) is not None

    def reset(self):
        """Drop every instrument (tests; a fresh registry for a fresh
        scenario).  Call sites that cached instrument objects keep
        recording into orphans — re-create after reset."""
        with self._lock:
            self._instruments.clear()


_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    """THE process-wide registry every subsystem records into."""
    return _default
