"""Per-process observability HTTP endpoint (DESIGN-OBSERVABILITY.md
§Distributed plane).

PR 8 made every process answer ``scrape()`` *from inside*; this module
makes it answer from *outside*: a stdlib ``ThreadingHTTPServer`` on a
loopback port serving

- ``/metrics``       — Prometheus text exposition (the registry, with
  the process's ``rank`` merged into every sample's labels);
- ``/metrics.json``  — the ``export.dump_json`` shape (metrics
  snapshot + trace summary) the fleet aggregator consumes;
- ``/trace``         — Chrome/Perfetto ``trace_event`` JSON of the
  span ring (empty ``traceEvents`` when tracing is disarmed);
- ``/events``        — the control-loop decision ring
  (``observability.events``): drain/scale/shed decisions with
  timestamps, host-state only;
- ``/healthz``       — liveness probe; answers from already-host
  state only, so it stays responsive even while a ``/metrics`` scrape
  is wedged on a device materialization (each request runs on its own
  daemon thread).

Arming contract (mirrors ``PADDLE_TPU_TRACE``):

- **Off by default, zero overhead when disarmed.**  With
  ``PADDLE_TPU_METRICS_PORT`` unset/empty/``0`` no thread and no
  socket is ever created — ``maybe_serve_from_env()`` returns None
  without touching the network stack (pinned in tests).
- **Per-rank port offsetting.**  N ranks on one host inherit the SAME
  env; each binds its own port so they never collide:
  ``base`` for a process without a rank (single-process training, or
  the launch controller), ``base + 1 + rank`` for rank *r* (the
  ``PADDLE_TRAINER_ID`` env the launch controllers already set).
  Parked spares (``PADDLE_RANK_ROLE=spare``) do not serve — they have
  no rank yet; :func:`serve_for_rank` arms them at promotion time,
  on their dead predecessor's (now free) port.
- **Scrape-time-only materialization.**  The handler calls the same
  ``export`` surfaces as in-process ``scrape()`` — deferred lazy
  device values pay their D2H sync inside the request, which IS the
  sanctioned sync point of the host-sync contract
  (``scripts/check_host_sync.py`` guards this module like the hot
  loops feeding the registry).
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from ..framework import env_knobs
from . import events as _events
from . import export as _export
from . import trace as _trace
from .export import json_safe  # noqa: F401 — re-export: the wire-
# dialect helper lives in export.py (dump_json uses it too)
from .metrics import MetricsRegistry

__all__ = ["ObservabilityHTTPServer", "serve", "serve_for_rank",
           "maybe_serve_from_env", "active_server", "resolve_port",
           "json_safe"]

# Route handler: () -> (status, content_type, body_bytes)
RouteFn = Callable[[], Tuple[int, str, bytes]]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
JSON_CONTENT_TYPE = "application/json; charset=utf-8"


def _rank_from_env(env) -> Optional[int]:
    raw = env.get("PADDLE_TRAINER_ID", "")
    try:
        rank = int(raw)
    except (TypeError, ValueError):
        return None
    return rank if rank >= 0 else None


def resolve_port(env=None) -> Optional[int]:
    """The port THIS process should serve on, or None when disarmed.

    Layout (one env var, N processes, zero collisions):
    ``base`` when the process has no rank identity — single-process
    training, or a launch controller/supervisor; ``base + 1 + r`` for
    rank ``r``.  A parked spare resolves to None (no rank yet — see
    :func:`serve_for_rank`)."""
    env = env or os.environ
    raw = (env_knobs.get_raw("PADDLE_TPU_METRICS_PORT", env=env)
           or "").strip()
    if not raw:
        return None
    try:
        base = int(raw)
    except ValueError:
        return None
    if base <= 0:
        return None
    if env.get("PADDLE_RANK_ROLE") == "spare":
        return None
    rank = _rank_from_env(env)
    return base if rank is None else base + 1 + rank


class ObservabilityHTTPServer:
    """One process's scrape endpoint.  ``port=0`` binds an ephemeral
    port (tests); ``registry=None`` serves THE process-wide registry.
    ``extra_routes`` lets a supervisor (the launch controller) mount
    additional paths — ``/fleet/metrics`` et al. — on the same
    server; :meth:`add_route` mounts them after construction."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 extra_labels: Optional[Dict[str, str]] = None,
                 extra_routes: Optional[Dict[str, RouteFn]] = None):
        self.registry = registry
        self.extra_labels = dict(extra_labels or {})
        self._routes: Dict[str, RouteFn] = {
            "/metrics": self._metrics,
            "/metrics.json": self._metrics_json,
            "/trace": self._trace,
            "/events": self._events,
            "/healthz": self._healthz,
        }
        self._routes.update(extra_routes or {})
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # scrapes are machine traffic: no per-request stderr lines
            def log_message(self, *a):
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                fn = outer._routes.get(path)
                if fn is None:
                    status, ctype, body = 404, "text/plain", b"not found\n"
                else:
                    try:
                        status, ctype, body = fn()
                    except Exception as e:  # noqa: BLE001 — one bad
                        # scrape (failed lazy, mid-merge error) must
                        # answer 500, not kill the handler thread
                        status, ctype = 500, "text/plain"
                        body = (f"{type(e).__name__}: {e}\n"
                                ).encode("utf-8", "replace")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # impatient scraper; nothing to clean up

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        # a handler wedged mid-materialization must never block
        # process exit or close(): daemon handler threads, and close
        # does not join them
        self._httpd.daemon_threads = True
        self._httpd.block_on_close = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"paddle-tpu-metrics-{self.port}", daemon=True)
        self._thread.start()

    # -- route handlers ------------------------------------------------------
    def _metrics(self):
        text = _export.to_prometheus_text(
            self.registry, extra_labels=self.extra_labels or None)
        return 200, PROM_CONTENT_TYPE, text.encode("utf-8")

    def _metrics_json(self):
        payload = {"metrics": _export.snapshot(self.registry),
                   "trace_summary": _trace.summary()}
        return (200, JSON_CONTENT_TYPE,
                json.dumps(json_safe(payload), allow_nan=False,
                           default=str).encode("utf-8"))

    def _trace(self):
        return (200, JSON_CONTENT_TYPE,
                json.dumps(_trace.to_chrome_trace()).encode("utf-8"))

    def _events(self):
        # host-only like /healthz: the decision ring must answer
        # while a /metrics scrape is wedged on a device sync
        payload = {"events": _events.snapshot(),
                   "capacity": _events.capacity()}
        return (200, JSON_CONTENT_TYPE,
                json.dumps(json_safe(payload), allow_nan=False,
                           default=str).encode("utf-8"))

    def _healthz(self):
        # host state ONLY — must answer while a /metrics scrape is
        # blocked on a device sync (liveness ≠ scrapability)
        payload = {"status": "ok", "pid": os.getpid()}
        rank = self.extra_labels.get("rank")
        if rank is not None:
            payload["rank"] = rank
        return (200, JSON_CONTENT_TYPE,
                json.dumps(payload).encode("utf-8"))

    # -- lifecycle -----------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    def add_route(self, path: str, fn: RouteFn):
        """Mount an extra GET route (e.g. the controller's /fleet/*)
        on the running server."""
        self._routes[str(path)] = fn

    def close(self):
        """Stop accepting and release the socket.  In-flight handler
        threads are daemons and are not joined — a wedged scrape
        cannot wedge teardown."""
        try:
            self._httpd.shutdown()
        finally:
            self._httpd.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def serve(port: int, host: str = "127.0.0.1",
          registry: Optional[MetricsRegistry] = None,
          extra_labels: Optional[Dict[str, str]] = None,
          extra_routes: Optional[Dict[str, RouteFn]] = None
          ) -> ObservabilityHTTPServer:
    """Start an endpoint explicitly (``LLMServer(metrics_port=...)``,
    tests).  ``port=0`` = ephemeral.  The caller owns close()."""
    return ObservabilityHTTPServer(port, host=host, registry=registry,
                                   extra_labels=extra_labels,
                                   extra_routes=extra_routes)


# -- env-armed process singleton ---------------------------------------------
_active: Optional[ObservabilityHTTPServer] = None
_active_lock = threading.Lock()


def active_server() -> Optional[ObservabilityHTTPServer]:
    """The env-armed per-process endpoint (None when disarmed) — the
    launch controller reuses it for its /fleet/* routes instead of
    binding a second port."""
    return _active


def maybe_serve_from_env(env=None) -> Optional[ObservabilityHTTPServer]:
    """Arm the per-process endpoint iff ``PADDLE_TPU_METRICS_PORT``
    resolves to a port (idempotent).  Disarmed mode creates NOTHING —
    no socket, no thread.  A bind failure warns and leaves the
    process serving nothing: observability must never kill training."""
    global _active
    port = resolve_port(env)
    if port is None:
        return None
    with _active_lock:
        if _active is not None:
            return _active
        rank = _rank_from_env(env or os.environ)
        labels = {"rank": str(rank)} if rank is not None else None
        try:
            _active = serve(port, extra_labels=labels)
        except Exception as e:  # noqa: BLE001 — OSError on a busy
            # port, OverflowError on an out-of-range one: an armed-
            # but-unbindable endpoint must degrade, never kill the
            # package import that armed it
            warnings.warn(
                f"observability: could not bind metrics port {port} "
                f"({type(e).__name__}: {e}); /metrics disabled for "
                "this process")
            return None
        return _active


def serve_for_rank(rank: int, env=None
                   ) -> Optional[ObservabilityHTTPServer]:
    """Late arming for a promoted spare: it had no rank at import, so
    env arming skipped it; at promotion it takes over its dead
    predecessor's port (``base + 1 + rank`` — the predecessor was
    SIGKILLed by the controller, so the port is free).  No-op when the
    env is disarmed or an endpoint is already up."""
    global _active
    env = env or os.environ
    raw = (env_knobs.get_raw("PADDLE_TPU_METRICS_PORT", env=env)
           or "").strip()
    try:
        base = int(raw) if raw else 0
    except ValueError:
        base = 0
    if base <= 0:
        return None
    with _active_lock:
        if _active is not None:
            return _active
        try:
            _active = serve(base + 1 + int(rank),
                            extra_labels={"rank": str(int(rank))})
        except Exception as e:  # noqa: BLE001 — same degradation
            # contract as maybe_serve_from_env
            warnings.warn(
                "observability: promoted rank could not bind metrics "
                f"port {base + 1 + int(rank)} ({type(e).__name__}: "
                f"{e}); /metrics disabled for this process")
            return None
        return _active


def _reset_for_tests():
    """Close and forget the env-armed singleton (test isolation)."""
    global _active
    with _active_lock:
        if _active is not None:
            _active.close()
            _active = None
