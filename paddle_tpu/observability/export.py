"""Metric + trace export surfaces (DESIGN-OBSERVABILITY.md).

- :func:`snapshot` — one dict over every registered instrument, keyed
  ``name{label="v"}``; this is what ``paddle_tpu.observability
  .scrape()`` returns.  ``materialize=True`` (the default) pays the
  deferred device→host syncs of lazy-valued instruments HERE — the
  scrape is the sanctioned sync point, the instrumented loops never
  sync.
- :func:`to_prometheus_text` — Prometheus text exposition format
  (``# HELP``/``# TYPE``, cumulative ``le`` buckets) for anything
  that scrapes text endpoints.
- :func:`dump_json` — snapshot + trace summary in one JSON file, the
  compact per-run record bench rounds attach.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, Optional

from . import trace as _trace
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .metrics import _escape_label_value
from .metrics import registry as _registry

__all__ = ["snapshot", "to_prometheus_text", "dump_json"]


def snapshot(reg: Optional[MetricsRegistry] = None,
             materialize: bool = True) -> Dict[str, Dict[str, Any]]:
    """Scrape every instrument into one plain dict.

    ``materialize=True`` flushes deferred lazy values (the ONE
    device→host sync point of the metrics pipeline);
    ``materialize=False`` reads only already-host state — e.g. the
    watchdog dumping from a hung process must not block on device."""
    reg = reg or _registry()
    out: Dict[str, Dict[str, Any]] = {}
    for inst in reg.instruments():
        entry: Dict[str, Any] = {"type": inst.kind, "help": inst.help}
        if isinstance(inst, Histogram):
            entry.update(inst.collect(materialize=materialize))
        else:
            entry["value"] = inst.collect(materialize=materialize)
        if inst.pending_dropped:
            entry["pending_dropped"] = inst.pending_dropped
        out[inst.key()] = entry
    return out


def _prom_num(v) -> str:
    if v is None:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def to_prometheus_text(reg: Optional[MetricsRegistry] = None,
                       materialize: bool = True) -> str:
    """Prometheus text exposition of the registry."""
    reg = reg or _registry()
    lines = []
    seen_header = set()
    for inst in sorted(reg.instruments(), key=lambda i: i.key()):
        if inst.name not in seen_header:
            seen_header.add(inst.name)
            if inst.help:
                lines.append(f"# HELP {inst.name} {inst.help}")
            lines.append(f"# TYPE {inst.name} {inst.kind}")
        suffix = inst.labels_suffix()
        if isinstance(inst, Histogram):
            data = inst.collect(materialize=materialize)
            base = dict(inst.labels)
            for le, cum in data["buckets"]:
                lbl = ",".join(
                    [f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(base.items())]
                    + [f'le="{_prom_num(le)}"'])
                lines.append(
                    f"{inst.name}_bucket{{{lbl}}} {cum}")
            lines.append(f"{inst.name}_sum{suffix} "
                         f"{_prom_num(data['sum'])}")
            lines.append(f"{inst.name}_count{suffix} {data['count']}")
        else:
            v = inst.collect(materialize=materialize)
            if v is None:
                # valueless (dead-engine fn, unset, failed lazy):
                # absent sample, not a NaN series forever
                continue
            lines.append(f"{inst.name}{suffix} {_prom_num(v)}")
    return "\n".join(lines) + "\n"


def dump_json(path: str, reg: Optional[MetricsRegistry] = None) -> str:
    """Metrics snapshot + per-span trace summary in one JSON file."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    payload = {"metrics": snapshot(reg),
               "trace_summary": _trace.summary()}
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    return path
