"""Metric + trace export surfaces (DESIGN-OBSERVABILITY.md).

- :func:`snapshot` — one dict over every registered instrument, keyed
  ``name{label="v"}``; this is what ``paddle_tpu.observability
  .scrape()`` returns.  ``materialize=True`` (the default) pays the
  deferred device→host syncs of lazy-valued instruments HERE — the
  scrape is the sanctioned sync point, the instrumented loops never
  sync.
- :func:`to_prometheus_text` — Prometheus text exposition format
  (``# HELP``/``# TYPE``, cumulative ``le`` buckets) for anything
  that scrapes text endpoints.
- :func:`dump_json` — snapshot + trace summary in one JSON file, the
  compact per-run record bench rounds attach.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, Optional

from . import trace as _trace
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .metrics import _escape_label_value
from .metrics import registry as _registry

__all__ = ["snapshot", "to_prometheus_text", "dump_json",
           "json_safe"]


def json_safe(obj):
    """Recursively replace non-finite floats with their Prometheus
    string spellings (``"+Inf"``/``"-Inf"``/``"NaN"``).  Python's
    ``json.dumps`` emits bare ``Infinity`` tokens for them — valid to
    ``json.loads`` but rejected by RFC-8259 parsers (jq, JS
    ``JSON.parse``, Go), and every histogram snapshot carries a
    ``+Inf`` bucket edge, so an unsanitized export would be
    unreadable by exactly the external tooling it exists for.
    ``float("+Inf")`` round-trips, so numeric consumers stay one cast
    away.  Used by the HTTP endpoints AND :func:`dump_json` — wire
    and file exports speak the same dialect."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj == math.inf:
            return "+Inf"
        if obj == -math.inf:
            return "-Inf"
        return obj
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def snapshot(reg: Optional[MetricsRegistry] = None,
             materialize: bool = True) -> Dict[str, Dict[str, Any]]:
    """Scrape every instrument into one plain dict.

    ``materialize=True`` flushes deferred lazy values (the ONE
    device→host sync point of the metrics pipeline);
    ``materialize=False`` reads only already-host state — e.g. the
    watchdog dumping from a hung process must not block on device."""
    reg = reg or _registry()
    out: Dict[str, Dict[str, Any]] = {}
    for inst in reg.instruments():
        entry: Dict[str, Any] = {"type": inst.kind, "help": inst.help}
        if isinstance(inst, Histogram):
            entry.update(inst.collect(materialize=materialize))
        else:
            entry["value"] = inst.collect(materialize=materialize)
        if inst.pending_dropped:
            entry["pending_dropped"] = inst.pending_dropped
        out[inst.key()] = entry
    return out


def _prom_num(v) -> str:
    """Prometheus number rendering, shared with the fleet-merge
    re-renderer (aggregate.py).  Accepts the JSON-safe string
    spellings ("+Inf"/"-Inf"/"NaN") a snapshot picks up crossing the
    /metrics.json wire — `float` round-trips them."""
    if v is None:
        return "NaN"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if f != f:
        return "NaN"
    if f == math.inf:
        return "+Inf"
    if f == -math.inf:
        return "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


def _label_suffix(labels: Dict[str, str]) -> str:
    """``{k="v",...}`` rendering (sorted, escaped); empty string for
    no labels — shared by the registry exporter and the fleet-merge
    re-renderer in :mod:`.aggregate`."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def to_prometheus_text(reg: Optional[MetricsRegistry] = None,
                       materialize: bool = True,
                       extra_labels: Optional[Dict[str, str]] = None
                       ) -> str:
    """Prometheus text exposition of the registry.

    ``extra_labels`` are merged into every sample's label set — the
    per-rank HTTP endpoint serves with ``{"rank": "<r>"}`` so a
    fleet-wide scraper can tell N identical processes apart without
    relabeling config on its side."""
    reg = reg or _registry()
    lines = []
    seen_header = set()
    for inst in sorted(reg.instruments(), key=lambda i: i.key()):
        if inst.name not in seen_header:
            seen_header.add(inst.name)
            if inst.help:
                lines.append(f"# HELP {inst.name} {inst.help}")
            lines.append(f"# TYPE {inst.name} {inst.kind}")
        base = dict(inst.labels)
        if extra_labels:
            base.update(extra_labels)
        suffix = _label_suffix(base)
        if isinstance(inst, Histogram):
            data = inst.collect(materialize=materialize)
            for le, cum in data["buckets"]:
                lbl = ",".join(
                    [f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(base.items())]
                    + [f'le="{_prom_num(le)}"'])
                lines.append(
                    f"{inst.name}_bucket{{{lbl}}} {cum}")
            lines.append(f"{inst.name}_sum{suffix} "
                         f"{_prom_num(data['sum'])}")
            lines.append(f"{inst.name}_count{suffix} {data['count']}")
        else:
            v = inst.collect(materialize=materialize)
            if v is None:
                # valueless (dead-engine fn, unset, failed lazy):
                # absent sample, not a NaN series forever
                continue
            lines.append(f"{inst.name}{suffix} {_prom_num(v)}")
    return "\n".join(lines) + "\n"


def dump_json(path: str, reg: Optional[MetricsRegistry] = None) -> str:
    """Metrics snapshot + per-span trace summary in one JSON file."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    payload = {"metrics": snapshot(reg),
               "trace_summary": _trace.summary()}
    with open(path, "w") as f:
        json.dump(json_safe(payload), f, indent=1, allow_nan=False,
                  default=str)
    return path
