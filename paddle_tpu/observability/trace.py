"""Low-overhead span recorder: ONE host-side timeline for the whole
stack (DESIGN-OBSERVABILITY.md).

Every layer used to keep its own ad-hoc timing — ``AutoFoldTuner``
calibration numbers died inside ``framework/dispatch.py``, serving
latency lived in private dicts, bench rounds hand-rolled JSON.  This
module is the single sink: training dispatches, serving request
lifecycles, checkpoint IO and user ``RecordEvent`` annotations all
record into one process-wide monotonic-clock ring buffer, so one
export answers "where did this step/request spend its time".

Design constraints (the fold=8 microbench is the referee):

- **~zero cost when disabled.**  ``span(name)`` returns a shared
  no-op singleton without allocating; the only disabled-path work is
  one global check.  Arm with ``PADDLE_TPU_TRACE=1`` (read when
  ``paddle_tpu.observability`` imports) or :func:`enable`.
- **No host↔device syncs.**  The recorder touches ``time`` and a
  deque — never a device value.  ``scripts/check_host_sync.py``
  guards this module like the hot loops it instruments.
- **Bounded memory.**  Events land in a ``deque(maxlen=capacity)``
  ring (default 64K events, ``PADDLE_TPU_TRACE_CAPACITY``): a
  week-long serving process keeps the most recent window instead of
  growing without bound.
- **Thread-aware.**  Events carry their OS thread ident; per-thread
  *live* span stacks let the hang watchdog name the phase a wedged
  dispatch died in (:func:`live_spans`).

Clock: ``time.monotonic_ns()`` everywhere — the same clock the
serving ``RequestStats`` milestones use, so retroactive request
lifecycle spans (:func:`add_span`) land on the same timeline as live
``span()`` records.

Exporters: :func:`to_chrome_trace` / :func:`dump_chrome_trace` emit
Chrome/Perfetto ``trace_event`` JSON (``X`` complete events; nesting
is by containment per track); :func:`summary` aggregates per-name
count/total/avg/max for a compact run report.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "enable", "disable", "enabled", "span", "instant", "counter",
    "add_span", "live_spans", "events", "clear", "to_chrome_trace",
    "dump_chrome_trace", "summary", "set_track_name",
]

_DEFAULT_CAPACITY = 1 << 16

# module state — plain globals so the disabled fast path is one
# LOAD_GLOBAL + truth test
_enabled: bool = False
_ring: deque = deque(maxlen=_DEFAULT_CAPACITY)
_epoch_ns: int = time.monotonic_ns()
# wall-clock anchor of the monotonic epoch, captured back-to-back with
# it: exported so a multi-rank merge (aggregate.merge_traces) can
# shift each process's relative timestamps onto ONE fleet timeline
_epoch_unix_ns: int = time.time_ns()
# tid -> list[(name, t0_ns)] — the LIVE stack per thread, read by the
# hang watchdog; list append/pop are atomic under the GIL
_live: Dict[int, List] = {}
# explicit display names for synthetic tracks (serving slot lanes)
_track_names: Dict[int, str] = {}
_lock = threading.Lock()


# -- record shapes ----------------------------------------------------------
# ("X", name, tid, t0_ns, dur_ns, args)     complete span
# ("i", name, tid, t_ns, None, args)        instant event
# ("C", name, tid, t_ns, value, None)       counter sample


class _Span:
    """A live span: records on ``__exit__``.  Only allocated while
    tracing is enabled — the disabled path returns :data:`_NULL_SPAN`.
    """

    __slots__ = ("_name", "_args", "_tid", "_t0", "_stack", "_entry")

    def __init__(self, name: str, args):
        self._name = name
        self._args = args
        self._tid = threading.get_ident()
        stack = _live.get(self._tid)
        if stack is None:
            stack = _live.setdefault(self._tid, [])
        self._stack = stack
        self._t0 = time.monotonic_ns()
        self._entry = (name, self._t0)
        stack.append(self._entry)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic_ns()
        stack = self._stack
        if stack and stack[-1] is self._entry:
            stack.pop()
        else:
            # non-LIFO exit (explicit begin()/end() APIs may overlap):
            # remove THIS span's own entry wherever it sits, so the
            # live stack never strands a phantom open phase
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is self._entry:
                    del stack[i]
                    break
        _ring.append(("X", self._name, self._tid, self._t0,
                      t1 - self._t0, self._args))
        return False


class _NullSpan:
    """Shared disabled-mode span: entering/exiting allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


# -- recording API ----------------------------------------------------------


def span(name: str, args: Optional[Dict[str, Any]] = None):
    """Context manager recording one complete span.  When tracing is
    disabled this returns a shared no-op object — the hot loops call
    it unconditionally and pay only the enabled check.  ``args``
    (optional dict) rides into the Chrome trace event; hot sites that
    build an args dict should do so per *dispatch*, not per step."""
    if not _enabled:
        return _NULL_SPAN
    return _Span(name, args)


def instant(name: str, args: Optional[Dict[str, Any]] = None):
    """Zero-duration marker (Chrome ``i`` event)."""
    if not _enabled:
        return
    _ring.append(("i", name, threading.get_ident(),
                  time.monotonic_ns(), None, args))


def counter(name: str, value: float):
    """Timeline counter sample (Chrome ``C`` event) — e.g. queue depth
    over time.  For scrape-able process metrics use the metrics
    registry instead; this feeds the *timeline* view."""
    if not _enabled:
        return
    _ring.append(("C", name, threading.get_ident(),
                  time.monotonic_ns(), float(value), None))


def add_span(name: str, t0_s: float, t1_s: float,
             tid: Optional[int] = None,
             args: Optional[Dict[str, Any]] = None):
    """Record a span RETROACTIVELY from ``time.monotonic()`` second
    timestamps — the serving engine reconstructs each request's
    queued→prefill→decode lifecycle from its ``RequestStats``
    milestones at finalize time, on a synthetic per-slot track
    (``tid``).  Same clock as ``span()``, so both interleave correctly
    on one timeline."""
    if not _enabled or t1_s < t0_s:
        return
    _ring.append(("X", name,
                  tid if tid is not None else threading.get_ident(),
                  int(t0_s * 1e9), int((t1_s - t0_s) * 1e9), args))


def set_track_name(tid: int, name: str):
    """Display name for a synthetic track (Perfetto thread_name
    metadata) — the serving engine labels slot lanes this way."""
    with _lock:
        _track_names[int(tid)] = str(name)


# -- lifecycle --------------------------------------------------------------


def enable(capacity: Optional[int] = None):
    """Arm the recorder (idempotent).  ``capacity`` resizes the ring
    (drops recorded events); default keeps the current ring."""
    global _enabled, _ring
    with _lock:
        if capacity is not None and capacity != _ring.maxlen:
            _ring = deque(maxlen=int(capacity))
        _enabled = True


def disable():
    """Stop recording.  The ring is kept for export; :func:`clear`
    empties it."""
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def clear():
    _ring.clear()
    _live.clear()
    with _lock:
        _track_names.clear()


def events() -> List[tuple]:
    """Snapshot of the raw ring (oldest first)."""
    return list(_ring)


def live_spans() -> Dict[str, List[str]]:
    """The CURRENTLY-OPEN span stack of every traced thread,
    outermost first — the hang watchdog's phase attribution.  Keys are
    ``"<thread name> (<ident>)"``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for tid, stack in list(_live.items()):
        if not stack:
            continue
        label = f"{names.get(tid, '?')} ({tid})"
        out[label] = [name for name, _t0 in list(stack)]
    return out


# -- export -----------------------------------------------------------------


def to_chrome_trace() -> Dict[str, Any]:
    """Chrome/Perfetto ``trace_event`` JSON object: ``X`` complete
    events with microsecond timestamps relative to the recorder epoch,
    plus ``M`` thread-name metadata so tracks read as phases, not
    idents.  Load via chrome://tracing or ui.perfetto.dev."""
    pid = os.getpid()
    trace_events: List[Dict[str, Any]] = []
    tids = set()
    for rec in list(_ring):
        kind, name, tid, t_ns, extra, args = rec
        tids.add(tid)
        ev: Dict[str, Any] = {
            "name": name, "pid": pid, "tid": tid, "cat": "paddle_tpu",
            "ts": (t_ns - _epoch_ns) / 1e3,
        }
        if kind == "X":
            ev["ph"] = "X"
            ev["dur"] = extra / 1e3
            if args:
                ev["args"] = args
        elif kind == "i":
            ev["ph"] = "i"
            ev["s"] = "t"
            if args:
                ev["args"] = args
        else:                                     # "C"
            ev["ph"] = "C"
            ev["args"] = {"value": extra}
        trace_events.append(ev)
    thread_names = {t.ident: t.name for t in threading.enumerate()}
    with _lock:
        thread_names.update(_track_names)
    for tid in sorted(tids):
        trace_events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": thread_names.get(tid, f"thread-{tid}")},
        })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            # wall-clock anchor of ts=0 (extra top-level keys are
            # ignored by chrome://tracing and Perfetto; the multi-rank
            # merge uses it to align per-process timelines)
            "epochUnixNs": _epoch_unix_ns}


def dump_chrome_trace(path: str) -> str:
    """Write the timeline as Chrome-trace JSON; returns ``path``."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(to_chrome_trace(), f)
    return path


def summary() -> Dict[str, Dict[str, float]]:
    """Per-name aggregate over the recorded spans: count, total/avg/
    max milliseconds — the compact run report (``Profiler.summary``
    renders this)."""
    stats: Dict[str, Dict[str, float]] = {}
    for rec in list(_ring):
        if rec[0] != "X":
            continue
        _kind, name, _tid, _t0, dur_ns, _args = rec
        s = stats.setdefault(name, {"count": 0, "total": 0.0,
                                    "max": 0.0})
        ms = dur_ns / 1e6
        s["count"] += 1
        s["total"] += ms
        if ms > s["max"]:
            s["max"] = ms
    for s in stats.values():
        s["avg"] = s["total"] / s["count"]
    return stats
