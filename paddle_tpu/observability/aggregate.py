"""Multi-rank observability merge (DESIGN-OBSERVABILITY.md
§Distributed plane).

Every rank answers for itself over :mod:`.http`; this module turns N
per-rank answers into ONE fleet answer:

- :func:`merge_snapshots` — N ``export.snapshot()`` dicts → one dict
  with Prometheus-shaped semantics: **counters sum** across ranks
  (``fit_steps_total`` of the fleet is the sum of the ranks'),
  **gauges gain a ``rank`` label** (a last-write-wins value has no
  meaningful cross-rank sum — ``fit_loss{rank="1"}`` stays
  attributable), **histograms merge bucket-wise** (same fixed edges →
  cumulative bucket counts, sum and count add; conflicting edges
  raise exactly like the registry's explicit-edges conflict).  A name
  that changes *kind* across ranks raises ``TypeError`` like the
  registry's kind conflict — a name means one thing fleet-wide.
- :func:`merge_traces` — N per-rank Chrome traces → one fleet
  timeline: every rank becomes its own ``pid`` with a
  ``process_name`` metadata event (``rank0``, ``rank1``, …), and
  per-process relative timestamps are aligned onto one clock via the
  ``epochUnixNs`` anchor each exporter embeds (ranks whose traces
  lack the anchor merge unshifted).
- :func:`snapshot_to_prometheus_text` — re-render a (merged) snapshot
  dict as Prometheus text, so the controller's ``/fleet/metrics``
  serves the same exposition format as every per-rank ``/metrics``.
- :class:`StragglerDetector` — per-rank step-time from the beacon
  records the controller already polls (PR 9's liveness data):
  seconds-per-step over a sliding window, judged against the fleet
  median.  A rank slower than ``factor ×`` the median is a straggler
  — the controller exports ``fleet_straggler{rank=…}`` and logs the
  attribution.  (A rank making *zero* progress is the BeaconMonitor's
  wedge domain, not a straggler — no fresh window, no verdict.)

Everything here is host-side dict/list work on ALREADY-MATERIALIZED
snapshots — no device values, no syncs (the same contract
``scripts/check_host_sync.py`` enforces on the modules feeding it).
"""

from __future__ import annotations

import statistics
import time
from collections import deque
from typing import Any, Dict, List, Mapping, Optional

from .export import _prom_num
from .metrics import _escape_label_value

__all__ = ["merge_snapshots", "merge_traces",
           "snapshot_to_prometheus_text", "StragglerDetector"]


def _edge_list(buckets) -> List[Any]:
    """Bucket edges normalized for comparison: a snapshot that
    crossed the /metrics.json wire spells the +Inf edge ``"+Inf"``
    (RFC-8259 JSON has no Infinity token) while a local snapshot
    holds ``float('inf')`` — both must merge."""
    out = []
    for b in buckets:
        try:
            out.append(float(b[0]))
        except (TypeError, ValueError):
            out.append(b[0])
    return out


def _with_label(key: str, label: str, value: Any) -> str:
    """Append one label to a ``name{k="v"}``-shaped snapshot key
    textually — existing label values may contain escaped quotes, so
    splicing before the closing brace is the only safe edit that
    needs no parser."""
    lbl = f'{label}="{_escape_label_value(str(value))}"'
    if key.endswith("}"):
        return key[:-1] + "," + lbl + "}"
    return key + "{" + lbl + "}"


def merge_snapshots(snaps: Mapping[Any, Mapping[str, dict]],
                    rank_label: str = "rank") -> Dict[str, dict]:
    """Merge ``{rank_id: snapshot}`` into one fleet snapshot.

    ``rank_id`` keys become the ``rank`` label value for gauges (and
    any untyped entry); iteration is in sorted-key order so the merge
    is deterministic regardless of scrape arrival order."""
    out: Dict[str, dict] = {}
    kinds: Dict[str, str] = {}
    for rid in sorted(snaps, key=str):
        snap = snaps[rid]
        for key, entry in snap.items():
            kind = entry.get("type", "untyped")
            prev = kinds.get(key)
            if prev is not None and prev != kind:
                raise TypeError(
                    f"fleet merge: metric {key!r} is {prev} on one "
                    f"rank and {kind} on rank {rid!r} — a name means "
                    "one thing fleet-wide")
            kinds[key] = kind
            if kind == "counter":
                tgt = out.get(key)
                if tgt is None:
                    out[key] = dict(entry)
                else:
                    tgt["value"] = (tgt.get("value") or 0.0) + (
                        entry.get("value") or 0.0)
                    if entry.get("pending_dropped"):
                        tgt["pending_dropped"] = (
                            tgt.get("pending_dropped", 0)
                            + entry["pending_dropped"])
            elif kind == "histogram":
                tgt = out.get(key)
                if tgt is None:
                    out[key] = {**entry,
                                "buckets": [list(b) for b in
                                            entry.get("buckets", [])]}
                else:
                    edges_a = _edge_list(tgt["buckets"])
                    edges_b = _edge_list(entry.get("buckets", []))
                    if edges_a != edges_b:
                        raise ValueError(
                            f"fleet merge: histogram {key!r} bucket "
                            f"edges differ across ranks ({edges_a} vs "
                            f"{edges_b} on rank {rid!r})")
                    # cumulative-of-sum == sum-of-cumulative, so the
                    # exported cumulative counts add elementwise
                    for b, (_, cum) in zip(tgt["buckets"],
                                           entry["buckets"]):
                        b[1] += cum
                    tgt["sum"] = tgt.get("sum", 0.0) + entry.get(
                        "sum", 0.0)
                    tgt["count"] = tgt.get("count", 0) + entry.get(
                        "count", 0)
            else:
                # gauge (and anything untyped): per-rank attribution,
                # never a cross-rank sum
                out[_with_label(key, rank_label, rid)] = dict(entry)
    return out


def snapshot_to_prometheus_text(snap: Mapping[str, dict]) -> str:
    """Prometheus text exposition of a snapshot dict (the merged-
    fleet counterpart of ``export.to_prometheus_text``, which renders
    live registries)."""
    lines: List[str] = []
    seen_header = set()
    for key in sorted(snap):
        entry = snap[key]
        name, brace, labels = key.partition("{")
        suffix = brace + labels           # "" or '{k="v",...}'
        inner = labels[:-1] if suffix else ""   # drop trailing "}"
        if name not in seen_header:
            seen_header.add(name)
            if entry.get("help"):
                lines.append(f"# HELP {name} {entry['help']}")
            lines.append(f"# TYPE {name} {entry.get('type', 'untyped')}")
        if entry.get("type") == "histogram":
            for le, cum in entry.get("buckets", []):
                lbl = (inner + "," if inner else "") + \
                    f'le="{_prom_num(le)}"'
                lines.append(f"{name}_bucket{{{lbl}}} {cum}")
            lines.append(f"{name}_sum{suffix} "
                         f"{_prom_num(entry.get('sum', 0.0))}")
            lines.append(f"{name}_count{suffix} "
                         f"{entry.get('count', 0)}")
        else:
            v = entry.get("value")
            if v is None:
                continue                  # absent, not NaN-forever
            lines.append(f"{name}{suffix} {_prom_num(v)}")
    return "\n".join(lines) + "\n"


def merge_traces(traces: Mapping[Any, Mapping[str, Any]]
                 ) -> Dict[str, Any]:
    """Merge ``{rank_id: chrome_trace_dict}`` into one fleet timeline
    — rank *r*'s events land on ``pid=r`` with a ``process_name``
    metadata event, so Perfetto renders the fleet as parallel process
    groups (the ROADMAP's pid-keyed Chrome trace item).

    Timestamp alignment: each exporter embeds ``epochUnixNs`` (the
    wall-clock anchor of its relative ``ts=0``); when every input has
    it, each rank's events are shifted so all ranks share the EARLIEST
    anchor as ts=0 — cross-rank span overlap then reads true on one
    timeline.  Any input lacking the anchor merges unshifted."""
    events: List[Dict[str, Any]] = []
    ids = sorted(traces, key=str)
    anchors = {rid: traces[rid].get("epochUnixNs") for rid in ids}
    have_all = ids and all(isinstance(a, int) for a in anchors.values())
    t0 = min(anchors.values()) if have_all else None
    for idx, rid in enumerate(ids):
        try:
            pid = int(rid)
        except (TypeError, ValueError):
            pid = idx
        shift_us = ((anchors[rid] - t0) / 1e3) if have_all else 0.0
        for ev in traces[rid].get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if shift_us and "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            events.append(ev)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"rank{rid}"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": pid, "tid": 0,
                       "args": {"sort_index": pid}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class StragglerDetector:
    """Per-rank step-time attribution from progress-beacon polls.

    ``observe(rank, step)`` each controller tick with the step the
    rank's beacon reports; the detector keeps a sliding window of
    (time, step) points per rank and estimates seconds-per-step as
    the window's endpoints slope.  ``judge()`` compares every rank
    against the fleet median: slower than ``factor ×`` median ⇒
    straggler.  Judgment needs ≥2 ranks with estimates (a fleet of
    one has no peer to lag) and each estimate needs ≥2 distinct steps
    inside the window (a frozen rank is the BeaconMonitor's wedge
    domain — absence of an estimate is not a straggler verdict).
    """

    def __init__(self, factor: float = 2.0, window_s: float = 30.0,
                 max_points: int = 64):
        self.factor = float(factor)
        self.window_s = float(window_s)
        self.max_points = int(max_points)
        self._points: Dict[Any, deque] = {}   # rank -> (t, step)

    def observe(self, rank, step: Optional[int],
                now: Optional[float] = None):
        if step is None:
            return
        now = time.monotonic() if now is None else now
        dq = self._points.setdefault(
            rank, deque(maxlen=self.max_points))
        # one point per step VALUE: polling faster than the rank
        # steps must not flatten the slope
        if dq and dq[-1][1] == int(step):
            return
        dq.append((now, int(step)))

    def forget(self, rank):
        self._points.pop(rank, None)

    def step_time(self, rank, now: Optional[float] = None
                  ) -> Optional[float]:
        """Estimated seconds per step over the window (None without
        ≥2 distinct in-window step observations)."""
        dq = self._points.get(rank)
        if not dq:
            return None
        now = time.monotonic() if now is None else now
        pts = [(t, s) for t, s in dq if now - t <= self.window_s]
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        dstep = pts[-1][1] - pts[0][1]
        if dstep <= 0 or dt <= 0:
            return None
        return dt / dstep


    def judge(self, now: Optional[float] = None
              ) -> Dict[Any, Dict[str, Any]]:
        """``{rank: {"step_time_s", "median_s", "straggler"}}`` for
        every rank with an estimate this window."""
        now = time.monotonic() if now is None else now
        times = {r: st for r in self._points
                 if (st := self.step_time(r, now=now)) is not None}
        if len(times) < 2:
            return {r: {"step_time_s": st, "median_s": None,
                        "straggler": False}
                    for r, st in times.items()}
        # LOWER median: with an even fleet the plain median averages
        # the two middles, so in a 2-rank fleet the straggler itself
        # drags the bar halfway toward its own step-time and can never
        # exceed 2x it; the lower median encodes the healthy-majority
        # assumption and degenerates to "the healthy rank's pace" at
        # fleet size 2
        med = statistics.median_low(sorted(times.values()))
        return {r: {"step_time_s": st, "median_s": med,
                    "straggler": bool(med > 0
                                      and st > self.factor * med)}
                for r, st in times.items()}

    def stragglers(self, now: Optional[float] = None) -> List[Any]:
        return sorted((r for r, v in self.judge(now=now).items()
                       if v["straggler"]), key=str)
