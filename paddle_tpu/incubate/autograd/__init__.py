"""paddle.incubate.autograd parity (upstream incubate/autograd/ —
functional jvp/vjp/Jacobian/Hessian; the prim-rule machinery upstream
needs for higher-order is jax's composable transforms here)."""

from ...autograd.functional import (  # noqa
    jvp, vjp, jacobian, hessian, Jacobian, Hessian)


def enable_prim():
    """Upstream toggles its primitive-op lowering for higher-order
    autodiff; jax transforms compose natively, so this is a no-op kept
    for script compatibility."""


def disable_prim():
    pass


def prim_enabled():
    return True
