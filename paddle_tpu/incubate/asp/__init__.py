"""Automatic SParsity (parity: python/paddle/incubate/asp/ — ASPHelper,
prune_model, decorate, 2:4 semi-structured sparsity; SURVEY.md §2.2
"Incubate" row).

Upstream prunes FC/conv weights to the 2:4 pattern the A100 sparse
tensor cores execute.  TPU MXUs have no 2:4 hardware mode, so the
TPU-native value of ASP is the *algorithm*: train-time structured
pruning with mask preservation (prune → mask-respecting optimizer) so
models exported elsewhere (or simply sparsified for quality/size
research) match upstream behavior bit-for-bit.  Masks are applied as
elementwise multiplies, which XLA fuses into the consuming matmul.
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

# pruned models tracked weakly: a deleted model drops out of the set,
# releasing its masks (and immune to id() reuse)
_PRUNED_MODELS: "weakref.WeakSet" = weakref.WeakSet()


def _mask_1d_2to4(flat: np.ndarray) -> np.ndarray:
    """Keep the 2 largest-|w| of every 4 consecutive weights."""
    n = flat.shape[0]
    pad = (-n) % 4
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = np.abs(flat.reshape(-1, 4))
    order = np.argsort(groups, axis=1)          # ascending
    mask = np.ones_like(groups, dtype=bool)
    rows = np.arange(groups.shape[0])[:, None]
    mask[rows, order[:, :2]] = False            # drop the 2 smallest
    mask = mask.reshape(-1)
    return mask[:n] if pad else mask


def create_mask(weight: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """n:m sparsity mask along the input dimension (paddle masks along
    the reduced dim of FC weights)."""
    if (n, m) != (2, 4):
        raise NotImplementedError("only 2:4 sparsity is supported")
    w = np.asarray(weight)
    if w.ndim < 2:
        return np.ones_like(w, dtype=bool)
    flat = w.reshape(-1)
    return _mask_1d_2to4(flat).reshape(w.shape)


def check_mask_2_4(weight: np.ndarray) -> bool:
    """True if every aligned group of 4 has ≤2 nonzeros."""
    flat = np.asarray(weight).reshape(-1)
    pad = (-flat.shape[0]) % 4
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    nz = (flat.reshape(-1, 4) != 0).sum(axis=1)
    return bool((nz <= 2).all())


def set_excluded_layers(model, layer_names: List[str]):
    if not hasattr(model, "_asp_excluded"):
        model._asp_excluded = set()
    model._asp_excluded.update(layer_names)


def reset_excluded_layers(model=None):
    if model is None:
        for m in list(_PRUNED_MODELS):
            if hasattr(m, "_asp_excluded"):
                m._asp_excluded = set()
        return
    if hasattr(model, "_asp_excluded"):
        model._asp_excluded = set()


def _prunable(model):
    """(name, param) pairs ASP prunes: ≥2-D weights of Linear/Conv-like
    layers, excluding user-excluded layer names."""
    excluded = getattr(model, "_asp_excluded", set())
    out = []
    for lname, layer in [("", model)] + [
            (n, l) for n, l in getattr(model, "named_sublayers",
                                       lambda: [])()]:
        if lname in excluded:
            continue
        w = getattr(layer, "weight", None)
        if w is None or w._value.ndim < 2:
            continue
        if type(layer).__name__ not in ("Linear", "Conv2D", "Conv1D",
                                        "Conv3D", "ColumnParallelLinear",
                                        "RowParallelLinear"):
            continue
        pname = f"{lname}.weight" if lname else "weight"
        out.append((pname, w))
    return out


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Prune every supported weight to n:m sparsity and remember the
    masks (on the model, tracked weakly) so ``decorate``-wrapped
    optimizers keep them zero."""
    masks = getattr(model, "_asp_masks", None)
    if masks is None:
        masks = model._asp_masks = {}
    for name, p in _prunable(model):
        mask = create_mask(np.asarray(p._value), n, m)
        jmask = jnp.asarray(mask, dtype=p._value.dtype)
        p._value = p._value * jmask
        if with_mask:
            masks[name] = (p, jmask)
    if with_mask:
        _PRUNED_MODELS.add(model)
    return masks


def decorate(optimizer):
    """Wrap an optimizer so every ``step()`` re-applies the pruning
    masks (upstream OptimizerWithSparsityGuarantee)."""
    return OptimizerWithSparsityGuarantee(optimizer)


class OptimizerWithSparsityGuarantee:
    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner"], item)

    def step(self):
        self._inner.step()
        # re-zero pruned weights (momentum/adam updates revive them);
        # only live pruned models are touched (WeakSet)
        for model in list(_PRUNED_MODELS):
            for p, jmask in getattr(model, "_asp_masks", {}).values():
                p._value = p._value * jmask

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, **kwargs):
        loss.backward()
        self.step()
        self._inner.clear_grad()
        return None, None
