from . import functional  # noqa
from .layers import (  # noqa
    FusedMultiHeadAttention, FusedFeedForward,
    FusedTransformerEncoderLayer)
