from . import functional  # noqa
