"""incubate fused-op APIs (upstream: paddle/incubate/nn/functional/ —
fused_multi_head_attention etc., backed by hand-fused CUDA in
paddle/fluid/operators/fused/).  On TPU these alias the composable ops:
XLA fusion produces the same fused kernels the CUDA versions hand-code
(SURVEY.md §2.1 "Fused transformer ops": "XLA fusion does most")."""

from ....ops.nn_ops import scaled_dot_product_attention  # noqa
from ....ops.nn_ops import linear as fused_linear  # noqa


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Upstream fused_multi_head_attention (fused_attention CUDA op)
    semantics, composed for XLA fusion: optional pre-LN → fused QKV
    projection → scaled-dot-product attention (+mask, +attn dropout) →
    output projection → dropout → residual add → optional post-LN.

    ``qkv_weight``: [3, num_heads, head_dim, embed_dim] (paddle layout;
    [embed_dim, 3*embed_dim] with ``transpose_qkv_wb=True``)."""
    import jax.numpy as jnp
    from ....ops import _primitive
    from ....ops.nn_ops import (layer_norm, dropout,
                                scaled_dot_product_attention)
    from ....ops import matmul, reshape, transpose
    from ....tensor import Tensor

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention(cache_kv=...) decode caching is "
            "not implemented; use nn.MultiHeadAttention with explicit "
            "cache handling")
    if ring_id not in (-1, None):
        raise NotImplementedError(
            "fused_multi_head_attention(ring_id>=0): tensor parallelism "
            "on TPU is expressed via fleet.meta_parallel mp layers "
            "(SPMD), not NCCL ring ids")
    residual = x
    out = x
    if pre_layer_norm:
        out = layer_norm(out, out.shape[-1:], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    b, s, e = out.shape
    if transpose_qkv_wb:
        nh = int(num_heads)
        if nh <= 0:
            raise ValueError(
                "num_heads must be given with transpose_qkv_wb=True")
        qkv = matmul(out, qkv_weight)                # [b, s, 3e]
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        qkv = reshape(qkv, [b, s, 3, nh, e // nh])
    else:
        w = qkv_weight  # [3, H, hd, E]
        nh = w.shape[1]
        hd = w.shape[2]
        flat_w = reshape(w, [3 * nh * hd, e])
        qkv = matmul(out, flat_w, transpose_y=True)  # [b, s, 3*H*hd]
        if qkv_bias is not None:
            qkv = qkv + reshape(qkv_bias, [3 * nh * hd])
        qkv = reshape(qkv, [b, s, 3, nh, hd])
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    ctx = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)
    ctx = reshape(ctx, [b, s, e])
    proj = matmul(ctx, linear_weight)
    if linear_bias is not None:
        proj = proj + linear_bias
    proj = dropout(proj, p=dropout_rate, training=training,
                   mode=mode)
    if add_residual:
        proj = residual + proj
    if not pre_layer_norm:
        proj = layer_norm(proj, proj.shape[-1:], ln_scale, ln_bias,
                          ln_epsilon)
    return proj


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, name=None):
    """Upstream fused_feedforward semantics:
    ``residual + dropout2(linear2(dropout1(act(linear1(ln?(x))))))``
    with pre- or post-LN."""
    from ....ops.nn_ops import layer_norm, dropout
    from ....ops import matmul
    from .... import ops as _ops

    if ring_id not in (-1, None):
        raise NotImplementedError(
            "fused_feedforward(ring_id>=0): use fleet.meta_parallel mp "
            "layers for tensor parallelism on TPU")
    residual = x
    out = x
    if pre_layer_norm:
        out = layer_norm(out, out.shape[-1:], ln1_scale, ln1_bias,
                         ln1_epsilon)
    out = matmul(out, linear1_weight)
    if linear1_bias is not None:
        out = out + linear1_bias
    act = getattr(_ops, activation)
    out = act(out)
    out = dropout(out, p=dropout1_rate, training=training,
                  mode=mode)
    out = matmul(out, linear2_weight)
    if linear2_bias is not None:
        out = out + linear2_bias
    out = dropout(out, p=dropout2_rate, training=training,
                  mode=mode)
    out = residual + out
    if not pre_layer_norm:
        out = layer_norm(out, out.shape[-1:], ln2_scale, ln2_bias,
                         ln2_epsilon)
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, *args,
                     **kwargs):
    from ....ops.nn_ops import layer_norm
    return layer_norm(x, x.shape[-1:], norm_weight, norm_bias, epsilon)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True):
    from ....ops.nn_ops import layer_norm, dropout
    out = x if bias is None else x + bias
    out = dropout(out, p=dropout_rate, training=training)
    out = out + residual
    return layer_norm(out, out.shape[-1:], ln_scale, ln_bias, ln_epsilon)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1):
    from ....ops.nn_ops import rms_norm
    return rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """RoPE (upstream fused_rope CUDA kernel) — composed form, fused by
    XLA."""
    import jax.numpy as jnp
    from ....ops._primitive import primitive

    @primitive(name="rope_apply")
    def _rope(t, sin_, cos_):
        # t: [b, s, h, d]
        if use_neox_rotary_style:
            d = t.shape[-1]
            t1, t2 = t[..., : d // 2], t[..., d // 2:]
            rot = jnp.concatenate([-t2, t1], axis=-1)
        else:
            t1 = t[..., ::2]
            t2 = t[..., 1::2]
            rot = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * cos_ + rot * sin_

    outs = []
    for t in (q, k, v):
        outs.append(None if t is None else _rope(t, sin, cos))
    return tuple(outs)
