"""incubate fused-op APIs (upstream: paddle/incubate/nn/functional/ —
fused_multi_head_attention etc., backed by hand-fused CUDA in
paddle/fluid/operators/fused/).  On TPU these alias the composable ops:
XLA fusion produces the same fused kernels the CUDA versions hand-code
(SURVEY.md §2.1 "Fused transformer ops": "XLA fusion does most")."""

from ....ops.nn_ops import scaled_dot_product_attention  # noqa
from ....ops.nn_ops import linear as fused_linear  # noqa


def fused_multi_head_attention(x, qkv_weight, linear_weight, *args,
                               **kwargs):
    raise NotImplementedError(
        "fused_multi_head_attention: use nn.MultiHeadAttention — XLA "
        "fuses the composed form on TPU")


def fused_feedforward(x, linear1_weight, linear2_weight, *args, **kwargs):
    raise NotImplementedError(
        "fused_feedforward: use Linear+activation — XLA fuses on TPU")


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, *args,
                     **kwargs):
    from ....ops.nn_ops import layer_norm
    return layer_norm(x, x.shape[-1:], norm_weight, norm_bias, epsilon)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True):
    from ....ops.nn_ops import layer_norm, dropout
    out = x if bias is None else x + bias
    out = dropout(out, p=dropout_rate, training=training)
    out = out + residual
    return layer_norm(out, out.shape[-1:], ln_scale, ln_bias, ln_epsilon)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1):
    from ....ops.nn_ops import rms_norm
    return rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """RoPE (upstream fused_rope CUDA kernel) — composed form, fused by
    XLA."""
    import jax.numpy as jnp
    from ....ops._primitive import primitive

    @primitive(name="rope_apply")
    def _rope(t, sin_, cos_):
        # t: [b, s, h, d]
        if use_neox_rotary_style:
            d = t.shape[-1]
            t1, t2 = t[..., : d // 2], t[..., d // 2:]
            rot = jnp.concatenate([-t2, t1], axis=-1)
        else:
            t1 = t[..., ::2]
            t2 = t[..., 1::2]
            rot = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * cos_ + rot * sin_

    outs = []
    for t in (q, k, v):
        outs.append(None if t is None else _rope(t, sin, cos))
    return tuple(outs)
