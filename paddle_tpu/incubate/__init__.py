"""paddle.incubate parity (staging ground — python/paddle/incubate/).
Grown as features land; nn.functional fused ops alias the main ops
(XLA fuses them anyway, which is the whole point on TPU)."""

from . import distributed  # noqa
from . import nn  # noqa
from . import asp  # noqa
from . import autograd  # noqa
from . import optimizer  # noqa
from .optimizer import LookAhead, ModelAverage  # noqa
