"""paddle.incubate.optimizer (parity: python/paddle/incubate/optimizer/
— LookAhead and ModelAverage, the two dygraph wrapper optimizers).

Both wrap an inner optimizer and keep auxiliary parameter copies; the
copies live as jnp arrays and the update math is pure, so the wrappers
compose with the compiled engines the same way the inner optimizers
do."""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor
from ..optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    """k-step lookahead (Zhang et al. 2019): every ``k`` inner steps,
    slow weights move ``alpha`` toward the fast weights and the fast
    weights reset to the slow ones."""

    def __init__(self, inner_optimizer: Optimizer, alpha: float = 0.5,
                 k: int = 5, name: Optional[str] = None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._parameter_list = inner_optimizer._parameter_list
        self._slow: Dict[int, jnp.ndarray] = {}
        self._step_count = 0
        # base-class state the inherited Optimizer API dereferences
        self._state: Dict[str, Dict] = {}
        self._learning_rate = inner_optimizer._learning_rate
        self._global_step = 0
        self._grad_clip = None
        self._opt_state_tree = None

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, value):
        self.inner_optimizer.set_lr(value)
        self._learning_rate = self.inner_optimizer._learning_rate

    def step(self):
        if not self._slow:
            for p in self._parameter_list:
                self._slow[id(p)] = p._value
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in self._parameter_list:
                slow = self._slow[id(p)]
                new_slow = slow + self.alpha * (p._value - slow)
                self._slow[id(p)] = new_slow
                p._value = new_slow

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["@LookAhead.step_count"] = self._step_count
        for i, p in enumerate(self._parameter_list):
            if id(p) in self._slow:
                sd[f"@LookAhead.slow_{i}"] = Tensor(
                    np.asarray(self._slow[id(p)]))
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(
            state_dict.pop("@LookAhead.step_count", 0))
        for i, p in enumerate(self._parameter_list):
            key = f"@LookAhead.slow_{i}"
            if key in state_dict:
                v = state_dict.pop(key)
                self._slow[id(p)] = jnp.asarray(
                    v.numpy() if isinstance(v, Tensor) else v)
        self.inner_optimizer.set_state_dict(state_dict)


class ModelAverage(Optimizer):
    """Running average of parameters (upstream ModelAverage): keeps
    sum_1/sum_2/sum_3 style accumulation reduced to one running sum +
    count; ``apply()`` swaps averaged weights in (context manager),
    ``restore()`` swaps back."""

    def __init__(self, average_window_rate: float, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        if parameters is None:
            raise ValueError("parameters is required in dygraph mode")
        self._parameter_list = list(parameters)
        self.avg_rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._sum: Dict[int, jnp.ndarray] = {}
        self._count = 0
        self._backup: Dict[int, jnp.ndarray] = {}
        self._state: Dict[str, Dict] = {}
        self._learning_rate = 0.0
        self._global_step = 0
        self._grad_clip = None
        self._opt_state_tree = None

    def get_lr(self):
        return 0.0

    def state_dict(self):
        out = {"@ModelAverage.count": self._count}
        for i, p in enumerate(self._parameter_list):
            if id(p) in self._sum:
                out[f"@ModelAverage.sum_{i}"] = Tensor(
                    np.asarray(self._sum[id(p)]))
        return out

    def set_state_dict(self, state_dict):
        self._count = int(state_dict.get("@ModelAverage.count", 0))
        for i, p in enumerate(self._parameter_list):
            key = f"@ModelAverage.sum_{i}"
            if key in state_dict:
                v = state_dict[key]
                self._sum[id(p)] = jnp.asarray(
                    v.numpy() if isinstance(v, Tensor) else v)

    def step(self):
        """Accumulate the current weights into the running average
        (call after the inner optimizer's step)."""
        window = max(self.min_window,
                     min(self.max_window,
                         int(self._count * self.avg_rate) + 1))
        for p in self._parameter_list:
            s = self._sum.get(id(p))
            self._sum[id(p)] = p._value if s is None else s + p._value
        self._count += 1
        if self._count > window:
            # slide: decay the sum so the window stays bounded
            scale = window / self._count
            for k in self._sum:
                self._sum[k] = self._sum[k] * scale
            self._count = window

    def apply(self, executor=None, need_restore: bool = True):
        """Swap averaged weights in; use as a context manager."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._backup = {id(p): p._value
                            for p in self._parameter_list}
            n = max(self._count, 1)
            for p in self._parameter_list:
                if id(p) in self._sum:
                    p._value = (self._sum[id(p)] / n).astype(
                        p._value.dtype)
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return _ctx()

    def restore(self, executor=None):
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._value = self._backup[id(p)]
        self._backup = {}

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad
