from . import models  # noqa
