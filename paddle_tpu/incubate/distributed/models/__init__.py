from . import moe  # noqa
