"""Functional MoE dispatch ops (parity: paddle/fluid/operators/
collective/global_scatter_op.* / global_gather_op.* — the NCCL
all-to-all pair behind upstream MoELayer; SURVEY.md §2.1
"Collective c_ops").

On TPU these are ``lax.all_to_all`` over a named mesh axis inside a
traced region (shard_map / jit).  MoELayer itself does not call them —
its EP boundary is a sharding annotation (see moe_layer.py) — but the
functional forms are provided for scripts that used the raw ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .....tensor import Tensor
from ..... import ops
from .....distributed.shard_map_compat import axis_size as _axis_size


@ops.primitive(name="global_scatter")
def global_scatter(x, local_count=None, global_count=None, group=None,
                   axis_name: str = None):
    """Exchange per-expert token buffers rank→expert-owner.

    x: [E, C, d] dense per-expert buffers (all experts).  Inside a
    traced region with ``axis_name`` bound (shard_map over the EP axis)
    performs the all-to-all; otherwise (single group) it is identity.
    """
    name = axis_name or getattr(group, "axis_name", None)
    if name is not None and isinstance(x, jax.core.Tracer):
        n = _axis_size(name)
        e = x.shape[0]
        parts = x.reshape((n, e // n) + x.shape[1:])
        return lax.all_to_all(parts, name, split_axis=0, concat_axis=1,
                              tiled=False).reshape(
            (e // n, n * x.shape[1]) + x.shape[2:])
    return x


@ops.primitive(name="global_gather")
def global_gather(x, local_count=None, global_count=None, group=None,
                  axis_name: str = None):
    """Inverse of global_scatter: return expert outputs to token owners.

    x: [E_local, n·C, d] → [E, C, d]."""
    name = axis_name or getattr(group, "axis_name", None)
    if name is not None and isinstance(x, jax.core.Tracer):
        n = _axis_size(name)
        e_local, nc = x.shape[0], x.shape[1]
        parts = x.reshape((e_local, n, nc // n) + x.shape[2:])
        parts = jnp.moveaxis(parts, 1, 0)           # [n, E_local, C, d]
        out = lax.all_to_all(parts, name, split_axis=0, concat_axis=0,
                             tiled=False)
        return out.reshape((n * e_local, nc // n) + x.shape[2:])
    return x
