"""MoE gating (parity: python/paddle/incubate/distributed/models/moe/
gate/ — NaiveGate, GShardGate, SwitchGate; SURVEY.md §2.2 "EP (expert
parallel / MoE)").

TPU-native formulation: instead of upstream's index-based scatter
(assign_pos / scatter CUDA kernels), gating produces dense
``combine_weights``/``dispatch_mask`` tensors of static shape
[tokens, experts, capacity] (the GShard paper's einsum formulation).
Static shapes keep the whole MoE block jit-compilable and let the
dispatch/combine run as batched matmuls on the MXU; token-drop beyond
capacity is the standard capacity_factor semantics.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .....tensor import Tensor
from .....nn.layer import Layer
from .....nn import initializer as I
from ..... import ops


def _topk_gating_values(logits, k: int, capacity: int,
                        aux_loss_mode: str = "gshard"):
    """Pure-jnp gating core.

    logits: [T, E] float.  Returns (combine [T,E,C], dispatch [T,E,C],
    aux_loss scalar).  Gradients flow through combine (gate probs) and
    aux_loss; the routing itself (argmax, positions) is integral.
    """
    T, E = logits.shape
    C = capacity
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    masks = []          # k one-hot [T, E] routing masks
    gates = []          # k [T] selected-prob vectors
    remaining = probs
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        masks.append(m)
        gates.append(jnp.sum(probs * m, axis=-1))
        remaining = remaining * (1.0 - m)

    # aux (load-balance) loss on the top-1 assignment: E * Σ_e f_e·p_e
    # (Switch Transformer eq. 4 / GShard l_aux).
    f = jnp.mean(masks[0], axis=0)            # fraction routed to e
    p = jnp.mean(probs, axis=0)               # mean router prob for e
    aux_loss = E * jnp.sum(f * p)

    # buffer positions: slot-major cumulative count per expert so the
    # k-th choice queues behind all first choices (GShard order).
    positions = []
    prev_count = jnp.zeros((E,), jnp.float32)
    for m in masks:
        pos = jnp.cumsum(m, axis=0) - 1.0 + prev_count[None, :]
        prev_count = prev_count + jnp.sum(m, axis=0)
        positions.append(pos)

    keep = [m * (pos < C) for m, pos in zip(masks, positions)]

    # renormalise kept gate values over the k choices
    gate_sum = sum(g * jnp.sum(kp, axis=-1)
                   for g, kp in zip(gates, keep))
    denom = jnp.maximum(gate_sum, 1e-9)

    combine = jnp.zeros((T, E, C), jnp.float32)
    for g, kp, pos in zip(gates, keep, positions):
        slot = jax.nn.one_hot(pos.astype(jnp.int32), C,
                              dtype=jnp.float32)      # [T, E, C]
        w = (g / denom)[:, None] * kp                 # [T, E]
        combine = combine + w[:, :, None] * slot * kp[:, :, None]

    dispatch = (combine > 0.0).astype(jnp.float32)
    return combine, dispatch, aux_loss


@ops.primitive(name="topk_gating")
def topk_gating(logits, k=2, capacity=0):
    return _topk_gating_values(logits, k=k, capacity=capacity)


class BaseGate(Layer):
    def __init__(self, d_model: int, num_experts: int, top_k: int,
                 capacity_factor: float = 1.5, weight_attr=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            shape=[d_model, num_experts], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.loss: Optional[Tensor] = None   # set each forward (upstream
        #                                      convention: gate.get_loss())

    def capacity(self, num_tokens: int) -> int:
        c = int(math.ceil(num_tokens * self.top_k * self.capacity_factor
                          / self.num_experts))
        return max(c, self.top_k)

    def get_loss(self, clear: bool = True) -> Optional[Tensor]:
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    def forward(self, x):
        """x: [T, d_model] → (combine [T,E,C], dispatch [T,E,C])."""
        logits = ops.matmul(x, self.weight)
        cap = self.capacity(x.shape[0])
        combine, dispatch, aux = topk_gating(
            logits, k=self.top_k, capacity=cap)
        self.loss = aux
        return combine, dispatch


class NaiveGate(BaseGate):
    """Top-k softmax gate, no auxiliary loss used by caller (loss still
    computed; upstream NaiveGate also skips the balance loss)."""

    def __init__(self, d_model, num_expert=None, world_size=1, topk=2,
                 num_experts=None, **kw):
        e = num_experts if num_experts is not None else \
            (num_expert or 1) * world_size
        super().__init__(d_model, e, top_k=topk, **kw)


class SwitchGate(BaseGate):
    """Top-1 routing with load-balance loss (Switch Transformer)."""

    def __init__(self, d_model, num_expert=None, world_size=1, topk=1,
                 switch_eps=0.1, capacity=None, num_experts=None, **kw):
        e = num_experts if num_experts is not None else \
            (num_expert or 1) * world_size
        kw.setdefault("capacity_factor", 1.25)
        super().__init__(d_model, e, top_k=1, **kw)


class GShardGate(BaseGate):
    """Top-2 routing with load-balance loss (GShard)."""

    def __init__(self, d_model, num_expert=None, world_size=1, topk=2,
                 capacity=None, group=None, num_experts=None, **kw):
        e = num_experts if num_experts is not None else \
            (num_expert or 1) * world_size
        super().__init__(d_model, e, top_k=2, **kw)
