"""Mixture-of-Experts layer with expert parallelism (parity:
python/paddle/incubate/distributed/models/moe/moe_layer.py — MoELayer
with global_scatter/global_gather all-to-all dispatch; SURVEY.md §2.2
"EP (expert parallel / MoE)").

TPU-native design: upstream dispatches tokens with index-building CUDA
kernels (assign_pos, limit_by_capacity) + NCCL all-to-all
(global_scatter/global_gather ops).  Here dispatch/combine are dense
einsums against the gate's [tokens, experts, capacity] masks — batched
matmuls on the MXU — and expert parallelism is a sharding annotation on
the expert axis: ``dispatched [E, C, d]`` carries a PartitionSpec
('mp' by default) so under jit the XLA SPMD partitioner inserts the
all-to-all over ICI exactly where upstream calls global_scatter.  On a
single chip the same code runs dense (no collective), so loss-parity
tests vs a serial model hold.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .....tensor import Tensor
from .....nn.layer import Layer
from .....nn.container import LayerList
from .....nn import initializer as I
from ..... import ops
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate


def _constrain(t: Tensor, spec) -> Tensor:
    from .....distributed.fleet.meta_parallel.mp_layers import _constrain_op
    return _constrain_op(t, spec=spec)


class ExpertLayer(Layer):
    """One FFN expert (upstream ExpertLayer: fc1-act-fc2)."""

    def __init__(self, d_model: int, d_hidden: int, name=None,
                 activation="gelu"):
        super().__init__()
        from ..... import nn
        self.htoh4 = nn.Linear(d_model, d_hidden)
        self.h4toh = nn.Linear(d_hidden, d_model)
        self._act = activation

    def forward(self, x):
        h = self.htoh4(x)
        h = ops.gelu(h) if self._act == "gelu" else ops.relu(h)
        return self.h4toh(h)


class GroupedExpertsFFN(Layer):
    """All experts' FFN weights stacked on a leading expert axis, sharded
    on the EP mesh axis — the grouped-GEMM formulation (one batched
    einsum feeds the MXU instead of E small matmuls)."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 ep_axis: Optional[str] = None, activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.ep_axis = ep_axis
        self._act = activation
        self.w1 = self.create_parameter(
            shape=[num_experts, d_model, d_hidden],
            default_initializer=I.XavierUniform())
        self.b1 = self.create_parameter(
            shape=[num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter(
            shape=[num_experts, d_hidden, d_model],
            default_initializer=I.XavierUniform())
        self.b2 = self.create_parameter(
            shape=[num_experts, 1, d_model], is_bias=True)
        if ep_axis:  # None → dense (no EP): leave weights replicated
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.dist_spec = (ep_axis,) + (None,) * (len(p.shape) - 1)
                p.is_distributed = True

    def forward(self, dispatched):
        """dispatched: [E, C, d_model] → [E, C, d_model]."""
        h = ops.einsum("ecd,edh->ech", dispatched, self.w1) + self.b1
        h = ops.gelu(h) if self._act == "gelu" else ops.relu(h)
        return ops.einsum("ech,ehd->ecd", h, self.w2) + self.b2


def _make_gate(gate, d_model, num_experts, top_k):
    if isinstance(gate, BaseGate):
        return gate
    if isinstance(gate, dict):
        kind = gate.get("type", "gshard")
        top_k = gate.get("top_k", top_k)
    else:
        kind = gate or "gshard"
    kind = str(kind).lower()
    if kind in ("gshard",):
        return GShardGate(d_model, num_experts=num_experts)
    if kind in ("switch",):
        return SwitchGate(d_model, num_experts=num_experts)
    if kind in ("naive", "topk"):
        return NaiveGate(d_model, num_experts=num_experts, topk=top_k)
    raise ValueError(f"unknown gate {gate!r}")


class MoELayer(Layer):
    """paddle.incubate.distributed.models.moe.MoELayer parity.

    Args follow upstream: ``experts`` is a list/LayerList of expert
    Layers (each mapping [*, d_model] → [*, d_model]) OR a
    GroupedExpertsFFN; ``gate`` a BaseGate, dict, or name.  ``moe_group``
    selects the EP mesh axis (a communication.Group whose axis_name
    names a mesh axis); None → single-group dense execution.
    """

    def __init__(self, d_model: int, experts=None, gate=None,
                 moe_group=None, mp_group=None, num_experts: int = None,
                 d_hidden: int = None, top_k: int = 2,
                 recompute_interval: int = 0, name=None):
        super().__init__()
        if experts is None:
            if num_experts is None or d_hidden is None:
                raise ValueError(
                    "give either experts=[...] or num_experts+d_hidden")
            experts = GroupedExpertsFFN(
                num_experts, d_model, d_hidden,
                ep_axis=getattr(moe_group, "axis_name", None))
        if isinstance(experts, (list, tuple)):
            experts = LayerList(experts)
        self.experts = experts
        self.grouped = isinstance(experts, GroupedExpertsFFN)
        self.num_experts = experts.num_experts if self.grouped \
            else len(experts)
        self.d_model = d_model
        self.gate = _make_gate(gate, d_model, self.num_experts, top_k)
        self.moe_group = moe_group
        self._ep_axis = getattr(moe_group, "axis_name", None)
        self._recompute = recompute_interval

    @property
    def l_aux(self) -> Optional[Tensor]:
        """Balance loss of the last forward (add to the train loss)."""
        return self.gate.loss

    def _run_experts(self, dispatched: Tensor) -> Tensor:
        if self.grouped:
            return self.experts(dispatched)
        outs = [self.experts[i](dispatched[i])
                for i in range(self.num_experts)]
        return ops.stack(outs, axis=0)

    def forward(self, x):
        orig_shape = list(x.shape)
        x2 = ops.reshape(x, [-1, self.d_model])
        combine, dispatch = self.gate(x2)
        # dispatch: [T, E, C] 0/1 — routing is not differentiated
        dispatch = dispatch.detach() if hasattr(dispatch, "detach") \
            else dispatch
        dispatched = ops.einsum("tec,td->ecd", dispatch, x2)
        if self._ep_axis:
            # EP boundary: expert axis sharded → XLA emits the
            # all-to-all here (upstream: global_scatter)
            dispatched = _constrain(
                dispatched, (self._ep_axis, None, None))
        expert_out = self._run_experts(dispatched)
        if self._ep_axis:
            expert_out = _constrain(
                expert_out, (self._ep_axis, None, None))
        y = ops.einsum("tec,ecd->td", combine, expert_out)
        return ops.reshape(y, orig_shape[:-1] + [self.d_model])
