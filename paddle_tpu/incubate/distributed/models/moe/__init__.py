"""paddle.incubate.distributed.models.moe parity (SURVEY.md §2.2 "EP")."""

from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa
from .moe_layer import (ExpertLayer, GroupedExpertsFFN,  # noqa
                        MoELayer)
from .utils import global_gather, global_scatter  # noqa
