"""paddle.utils parity surface (python/paddle/utils/): run_check install
verification plus small helpers."""

from __future__ import annotations


def run_check() -> None:
    """Upstream paddle.utils.run_check(): verify the install can build a
    model and run a compiled train step on the available device(s)."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.tensor import Tensor

    dev = jax.devices()[0]
    print(f"Running verify PaddlePaddle-TPU program ... "
          f"device: {dev.platform}:{dev.id}")
    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    x = Tensor(np.random.RandomState(0).rand(4, 8).astype(np.float32))
    loss0 = None
    for _ in range(3):
        loss = (net(x) ** 2.0).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        loss0 = loss0 if loss0 is not None else float(loss.numpy())
    if not float(loss.numpy()) <= loss0:
        raise RuntimeError(
            "PaddlePaddle-TPU run_check failed: the train step did not "
            f"reduce the loss ({loss0} -> {float(loss.numpy())})")
    n = len(jax.devices())
    print(f"PaddlePaddle-TPU works! {n} device(s) available.")


def try_import(name: str):
    """paddle.utils.try_import parity."""
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(
            f"{name} is required for this feature; please install it "
            f"(e.g. `pip install {name}`): {e}") from e


def flatten(nested) -> list:
    out = []
    stack = [nested]
    while stack:
        cur = stack.pop()
        if isinstance(cur, (list, tuple)):
            stack.extend(reversed(cur))
        elif isinstance(cur, dict):
            # sorted-key order: matches upstream paddle.utils.flatten
            # (tf.nest-style) AND jax's dict-pytree leaf order
            stack.extend(cur[k] for k in sorted(cur, reverse=True))
        else:
            out.append(cur)
    return out


from . import cpp_extension  # noqa: E402
