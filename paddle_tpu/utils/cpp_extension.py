"""paddle.utils.cpp_extension — custom C++ operator extensions.

Parity: upstream's custom-operator toolchain
(``python/paddle/utils/cpp_extension/`` — ``load``/``setup`` compiling
``PD_BUILD_OP`` sources into importable ops).  Upstream JIT-compiles
C++/CUDA against libpaddle and registers kernels into the PHI registry.

TPU-native stance: there is no device-side C++ ABI to compile against —
device kernels are Pallas (``ops/pallas_ops.py`` is the template).
What a C++ extension CAN add on TPU is a **host operator**: the
compiled function runs on the host CPU and is stitched into compiled
programs as an XLA host callback (``jax.pure_callback``), which is also
how it stays usable eagerly and under ``@to_static``/jit.  Gradients
are supported by supplying a second C symbol (upstream's backward-op
analog) that becomes the op's custom VJP.

C ABI (fixed for every op; all buffers are contiguous row-major):

.. code-block:: c

    // forward: read n_ins input buffers, write the output buffer
    void op(const float** ins, const int64_t** shapes,
            const int32_t* ndims, int32_t n_ins,
            float* out, const int64_t* out_shape, int32_t out_ndim);

    // backward (optional): inputs + upstream grad -> per-input grads
    void op_grad(const float** ins, const int64_t** shapes,
                 const int32_t* ndims, int32_t n_ins,
                 const float* grad_out, const int64_t* gout_shape,
                 int32_t gout_ndim, float** grad_ins);

Usage::

    mod = paddle.utils.cpp_extension.load(
        name="my_ext", sources=["relu2.cc"])
    relu2 = mod.def_op("relu2", grad_symbol="relu2_grad")
    y = relu2(x)            # Tensor in/out, tape-recorded, jit-safe
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = ["load", "load_inline", "CppExtension"]

_F32P = ctypes.POINTER(ctypes.c_float)
_I64P = ctypes.POINTER(ctypes.c_int64)
_lock = threading.Lock()


def _default_build_dir(name: str) -> str:
    from ..framework import env_knobs
    root = env_knobs.get_raw(
        "PADDLE_TPU_EXTENSION_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "paddle_tpu_extensions"))
    return os.path.join(root, name)


def _compile(name: str, sources: Sequence[str],
             extra_cxx_flags: Sequence[str],
             build_directory: Optional[str], verbose: bool) -> str:
    bdir = build_directory or _default_build_dir(name)
    os.makedirs(bdir, exist_ok=True)
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_cxx_flags).encode())
    so = os.path.join(bdir, f"{name}_{h.hexdigest()[:16]}.so")
    with _lock:
        if not os.path.exists(so):
            # compile to a tmp path and os.rename into place: rename is
            # atomic on one filesystem, so a CONCURRENT PROCESS never
            # dlopens a half-written .so (the exists-check is then a
            # true commit point; the threading lock only covers threads)
            tmp = f"{so}.tmp.{os.getpid()}"
            cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
                   + list(extra_cxx_flags) + list(sources) + ["-o", tmp])
            if verbose:
                print("cpp_extension:", " ".join(cmd))
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise RuntimeError(
                    f"cpp_extension build of {name!r} failed:\n"
                    f"{proc.stderr[-4000:]}")
            os.replace(tmp, so)
    return so


class CppExtension:
    """A loaded extension library; ``def_op`` binds C symbols as ops."""

    def __init__(self, name: str, so_path: str):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)

    def _symbol(self, sym: str):
        try:
            return getattr(self._lib, sym)
        except AttributeError:
            raise AttributeError(
                f"extension {self.name!r} has no symbol {sym!r}; "
                "declare it extern \"C\"") from None

    def def_op(self, symbol: str, grad_symbol: Optional[str] = None,
               out_shape: Optional[Callable] = None,
               dtype: str = "float32") -> Callable:
        """Bind C symbol ``symbol`` as a framework op.

        ``out_shape(*input_shapes) -> shape``: defaults to input 0's
        shape.  ``grad_symbol``: optional backward symbol (see module
        docstring ABI) enabling autograd through the op.
        """
        import jax
        import jax.numpy as jnp
        fwd_c = self._symbol(symbol)
        fwd_c.restype = None
        bwd_c = self._symbol(grad_symbol) if grad_symbol else None
        if bwd_c is not None:
            bwd_c.restype = None
        np_dtype = np.dtype(dtype)
        if np_dtype != np.float32:
            raise NotImplementedError(
                "cpp_extension v1 supports float32 buffers; cast at the "
                "call site (the host callback would copy anyway)")
        shape_fn = out_shape or (lambda *shapes: shapes[0])

        def _marshal(arrays):
            arrays = [np.ascontiguousarray(a, dtype=np.float32)
                      for a in arrays]
            n = len(arrays)
            ins = (_F32P * n)(*[a.ctypes.data_as(_F32P) for a in arrays])
            shp_arrs = [np.asarray(a.shape, dtype=np.int64)
                        if a.ndim else np.zeros(1, np.int64)
                        for a in arrays]
            shapes = (_I64P * n)(*[s.ctypes.data_as(_I64P)
                                   for s in shp_arrs])
            ndims = (ctypes.c_int32 * n)(*[a.ndim for a in arrays])
            return arrays, ins, shapes, ndims, shp_arrs

        def host_fwd(*arrays):
            arrays, ins, shapes, ndims, keep = _marshal(arrays)
            oshape = tuple(int(d) for d in
                           shape_fn(*[a.shape for a in arrays]))
            out = np.zeros(oshape, np.float32)
            oshp = np.asarray(oshape, dtype=np.int64) \
                if out.ndim else np.zeros(1, np.int64)
            fwd_c(ins, shapes, ndims, ctypes.c_int32(len(arrays)),
                  out.ctypes.data_as(_F32P),
                  oshp.ctypes.data_as(_I64P),
                  ctypes.c_int32(out.ndim))
            return out

        def host_bwd(*arrays_and_g):
            arrays, g = arrays_and_g[:-1], arrays_and_g[-1]
            arrays, ins, shapes, ndims, keep = _marshal(arrays)
            g = np.ascontiguousarray(g, dtype=np.float32)
            gshp = np.asarray(g.shape, dtype=np.int64) \
                if g.ndim else np.zeros(1, np.int64)
            gouts = [np.zeros(a.shape, np.float32) for a in arrays]
            gptr = (_F32P * len(arrays))(
                *[go.ctypes.data_as(_F32P) for go in gouts])
            bwd_c(ins, shapes, ndims, ctypes.c_int32(len(arrays)),
                  g.ctypes.data_as(_F32P),
                  gshp.ctypes.data_as(_I64P), ctypes.c_int32(g.ndim),
                  gptr)
            return tuple(gouts)

        def raw_call(*vals):
            oshape = tuple(int(d) for d in
                           shape_fn(*[v.shape for v in vals]))
            sd = jax.ShapeDtypeStruct(oshape, jnp.float32)
            return jax.pure_callback(host_fwd, sd, *vals,
                                     vmap_method="sequential")

        if bwd_c is not None:
            raw_vjp = jax.custom_vjp(raw_call)

            def _f(*vals):
                return raw_call(*vals), vals

            def _b(res, g):
                sds = tuple(jax.ShapeDtypeStruct(v.shape, jnp.float32)
                            for v in res)
                outs = jax.pure_callback(host_bwd, sds, *res, g,
                                         vmap_method="sequential")
                return tuple(outs)

            raw_vjp.defvjp(_f, _b)
            impl = raw_vjp
        else:
            impl = raw_call

        from ..ops._primitive import primitive
        op = primitive(impl, name=f"{self.name}.{symbol}")
        op.__doc__ = (f"custom C++ host op {symbol!r} from "
                      f"{self.so_path} (XLA host callback)")
        return op


def load(name: str, sources: Sequence[str],
         extra_cxx_flags: Sequence[str] = (),
         build_directory: Optional[str] = None,
         verbose: bool = False) -> CppExtension:
    """Compile ``sources`` with g++ and return the loaded extension
    (upstream ``paddle.utils.cpp_extension.load`` shape).  Builds are
    content-hash cached in ``build_directory``."""
    if isinstance(sources, (str, os.PathLike)):
        sources = [sources]
    so = _compile(name, [os.fspath(s) for s in sources],
                  list(extra_cxx_flags), build_directory, verbose)
    return CppExtension(name, so)


def load_inline(name: str, cpp_source: str,
                extra_cxx_flags: Sequence[str] = (),
                build_directory: Optional[str] = None,
                verbose: bool = False) -> CppExtension:
    """Like :func:`load` but takes the C++ source as a string."""
    bdir = build_directory or _default_build_dir(name)
    os.makedirs(bdir, exist_ok=True)
    src = os.path.join(
        bdir, f"{name}_{hashlib.sha256(cpp_source.encode()).hexdigest()[:16]}.cc")
    if not os.path.exists(src):
        # atomic write (same discipline as _compile's .so rename): a
        # concurrent process must never read a half-written source
        tmp = f"{src}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(cpp_source)
        os.replace(tmp, src)
    return load(name, [src], extra_cxx_flags, bdir, verbose)
