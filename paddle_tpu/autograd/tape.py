"""Eager autograd: a lightweight op tape replayed under ``jax.vjp``.

Paddle's dygraph autograd builds a GradNode graph in C++ as ops execute
(upstream: paddle/fluid/eager/ — ``egr::GradNodeBase``, ``AutogradMeta``,
``egr::Backward()``; see SURVEY.md §2.1 "Eager autograd engine").  The
TPU-native equivalent records, per differentiable op call, the pure jax
function plus its inputs/outputs; ``backward()`` walks the tape in
reverse, computing each op's VJP with ``jax.vjp`` and accumulating
cotangents (the analog of ``GradTensorHolder``).

Design notes
------------
* The tape is global and append-only within a "graph generation".  Any op
  whose inputs include a ``stop_gradient=False`` tensor records a node.
* ``jax.vjp`` re-runs the op's forward to get the linearisation — eager
  backward therefore costs ~2× forward, like any tape with recompute.
  The jitted training path (``Model.fit`` fast path, ``@to_static``)
  bypasses the tape entirely with ``jax.value_and_grad`` over a
  functional call, where XLA dedupes the forward.
* Cotangent accumulation is keyed by tensor identity; leaf tensors get
  ``.grad`` populated (Paddle semantics: grads *accumulate* across
  backward calls until ``clear_grad``).
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

_grad_enabled: bool = True
_tape: List["TapeNode"] = []


class TapeNode:
    __slots__ = ("fn", "args", "arg_vals", "kwargs", "diff_idx", "outputs",
                 "name")

    def __init__(self, fn, args, arg_vals, kwargs, diff_idx, outputs, name):
        self.fn = fn              # pure fn over arrays
        self.args = args          # mixed Tensor / const positional args
        self.arg_vals = arg_vals  # values snapshotted at call time (jax
                                  # arrays are immutable, so this guards
                                  # against later in-place buffer swaps)
        self.kwargs = kwargs      # static (non-diff) kwargs
        self.diff_idx = diff_idx  # positions of tracked Tensor args
        self.outputs = outputs    # tuple of output Tensors
        self.name = name


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad_ctx():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = False
    try:
        yield
    finally:
        _grad_enabled = prev


class no_grad:
    """``paddle.no_grad`` — usable as context manager or decorator."""

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        def wrapper(*a, **kw):
            with no_grad_ctx():
                return fn(*a, **kw)
        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        set_grad_enabled(True)
        return self

    def __call__(self, fn):
        def wrapper(*a, **kw):
            prev = is_grad_enabled()
            set_grad_enabled(True)
            try:
                return fn(*a, **kw)
            finally:
                set_grad_enabled(prev)
        return wrapper


def record(fn: Callable, args: Sequence[Any], arg_vals: Sequence[Any],
           kwargs: Dict[str, Any], diff_idx: Sequence[int],
           outputs: Sequence[Any], name: str = "") -> None:
    _tape.append(TapeNode(fn, tuple(args), tuple(arg_vals), dict(kwargs),
                          tuple(diff_idx), tuple(outputs),
                          name or getattr(fn, "__name__", "op")))


def reset_tape() -> None:
    _tape.clear()


def tape_size() -> int:
    return len(_tape)


def _ones_like(val):
    return jnp.ones_like(val)


def _ct_like(ct, out_tensor):
    """Cast a cotangent to its primal output's dtype (amp O1 mixes
    float dtypes across consumer boundaries — the grad-dtype
    unification every branch of the walk must apply)."""
    want = out_tensor._value.dtype
    if getattr(ct, "dtype", want) != want and hasattr(ct, "astype") \
            and jnp.issubdtype(want, jnp.inexact):
        return ct.astype(want)
    return ct


def _node_vjp(node, cts):
    """VJP one tape node given the cotangent accumulator.

    Only inexact-dtype outputs participate (jax requires float0
    cotangents for integer primals — integer outputs like argmax indices
    simply don't carry gradient).  Returns cotangents aligned with
    ``node.diff_idx`` or None if nothing flows through this node.
    """
    if "__pylayer__" in node.kwargs:
        from .py_layer import _pylayer_vjp
        full = [cts.get(id(o)) for o in node.outputs]
        if all(c is None for c in full):
            return None
        full = [jnp.zeros_like(o._value) if c is None
                else _ct_like(c, o) for o, c in zip(node.outputs, full)]
        return _pylayer_vjp(node, full)
    eager_vjp = getattr(node.fn, "_eager_vjp", None)
    if eager_vjp is not None:
        # op supplies its own eager backward (may return SelectedRows
        # cotangents — e.g. sparse embedding grads)
        out_cts = [cts.get(id(o)) for o in node.outputs]
        if all(c is None for c in out_cts):
            return None
        out_cts = [jnp.zeros_like(o._value) if c is None
                   else _ct_like(c, o)
                   for o, c in zip(node.outputs, out_cts)]
        return eager_vjp(node, out_cts)
    out_idx = [j for j, o in enumerate(node.outputs)
               if jnp.issubdtype(o._value.dtype, jnp.inexact)]
    if not out_idx:
        return None
    out_cts = [cts.get(id(node.outputs[j])) for j in out_idx]
    if all(c is None for c in out_cts):
        return None
    # jax.vjp requires ct dtype == primal output dtype (see _ct_like)
    out_cts = [jnp.zeros_like(node.outputs[j]._value) if c is None
               else _ct_like(c, node.outputs[j])
               for j, c in zip(out_idx, out_cts)]
    diff_vals = [node.arg_vals[i] for i in node.diff_idx]

    def _f(*dvals, _node=node, _out_idx=tuple(out_idx)):
        vals = list(_node.arg_vals)
        for i, v in zip(_node.diff_idx, dvals):
            vals[i] = v
        out = _node.fn(*vals, **_node.kwargs)
        outs = out if isinstance(out, tuple) else (out,)
        return tuple(outs[j] for j in _out_idx)

    _, vjp_fn = jax.vjp(_f, *diff_vals)
    return vjp_fn(tuple(out_cts))


def _has_hooks(t) -> bool:
    hooks = getattr(t, "_grad_hooks", None)
    return bool(hooks) and any(h is not None for h in hooks)


def _apply_hooks(t, ct):
    """Run a tensor's grad hooks (registration order) on the fully
    accumulated cotangent; a hook returning non-None replaces it
    (upstream Tensor.register_hook contract)."""
    from ..tensor import Tensor
    for h in getattr(t, "_grad_hooks", ()):
        if h is None:
            continue
        out = h(Tensor(ct, stop_gradient=True))
        if out is not None:
            ct = out._value if hasattr(out, "_value") else jnp.asarray(out)
    return ct


def _finalize_hooked_outputs(node, cts, hook_done, deferred):
    """Called when the reverse walk reaches a node: every CONSUMER of
    this node's outputs has already been processed (the tape is
    chronological), so each output's cotangent is final — the moment
    registered grad hooks must fire.  If the tensor's ``.grad``
    assignment was deferred (hooked leaf-like), complete it with the
    hooked value."""
    for o in node.outputs:
        oid = id(o)
        if oid in hook_done or oid not in cts or not _has_hooks(o):
            continue
        cts[oid] = _apply_hooks(o, cts[oid])
        hook_done.add(oid)
        if oid in deferred:
            _add_grad(deferred.pop(oid), cts[oid])


def backward(tensors, grad_tensors=None, retain_graph: bool = False) -> None:
    """Reverse-walk the tape from ``tensors`` (usually one scalar loss).

    Populates ``.grad`` on every reachable leaf with
    ``stop_gradient=False`` and on non-leaves that called
    ``retain_grads()``.  Matches ``paddle.autograd.backward`` semantics.
    """
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangent accumulator keyed by tensor identity
    cts: Dict[int, Any] = {}
    for t, g in zip(tensors, grad_tensors):
        seed = _ones_like(t._value) if g is None else (
            g._value if hasattr(g, "_value") else jnp.asarray(g))
        _accum(cts, id(t), seed)

    produced = {id(o): n for n in _tape for o in n.outputs}
    hook_done: set = set()
    deferred: Dict[int, Any] = {}   # hooked tensors awaiting .grad

    for node in reversed(_tape):
        _finalize_hooked_outputs(node, cts, hook_done, deferred)
        in_cts = _node_vjp(node, cts)
        if in_cts is None:
            continue
        for i, ct in zip(node.diff_idx, in_cts):
            t = node.args[i]
            if ct is None or t.stop_gradient:
                continue
            _accum(cts, id(t), ct)
            wants_grad = (id(t) not in produced
                          or getattr(t, "_retain_grads", False))
            if wants_grad:
                if _has_hooks(t):
                    # defer: the hook must see the FULL accumulated
                    # grad, not each contribution
                    deferred[id(t)] = t
                else:
                    _add_grad(t, ct)

    # hooked leaves have no producer node — flush them now
    for tid, t in deferred.items():
        val = cts[tid]
        if tid not in hook_done:
            val = _apply_hooks(t, val)
        _add_grad(t, val)

    if not retain_graph:
        reset_tape()


def _accum(cts: Dict[int, Any], key: int, val) -> None:
    cur = cts.get(key)
    cts[key] = val if cur is None else cur + val


def _add_grad(t, ct) -> None:
    from ..tensor import Tensor
    from ..framework.selected_rows import SelectedRows
    if isinstance(ct, SelectedRows):
        # sparse grad stays sparse (paddle dygraph sparse semantics);
        # accumulation with an existing dense grad densifies
        if t.grad is None:
            t.grad = ct
        elif isinstance(t.grad, SelectedRows):
            t.grad = t.grad + ct
        else:
            t.grad = Tensor(ct + t.grad._value, stop_gradient=True)
        return
    if isinstance(t.grad, SelectedRows):
        t.grad = Tensor(t.grad + jnp.asarray(ct, dtype=t._value.dtype),
                        stop_gradient=True)
        return
    ct = jnp.asarray(ct, dtype=t._value.dtype)
    if t.grad is None:
        t.grad = Tensor(ct, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._value + ct, stop_gradient=True)


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    """``paddle.grad(create_graph=True)``: higher-order path.

    The eager walk computes grad VALUES but leaves no producing nodes
    on the tape, so a second ``paddle.grad`` would see them as unused.
    Here the input→output subgraph is REPLAYED as one pure function,
    its vjp is taken with ``jax.vjp``, and the whole computation is
    recorded back onto the tape as a closure op over ``inputs`` — the
    returned grads are then themselves differentiable (upstream
    double-grad semantics; SURVEY.md §4 autograd tests row).
    """
    from ..tensor import Tensor
    from ..ops._primitive import apply_closure

    nodes = list(_tape)
    in_ids = [id(t) for t in inputs]
    out_ids = [id(t) for t in outputs]

    # forward-reachable from inputs, then backward-reachable to outputs.
    # Static-mode FEED placeholders seed reachability too: a node
    # computed purely from a feed (param-free preprocessing) must be
    # REPLAYED, not baked at its placeholder value
    feed_ids = {id(a) for node in nodes for a in node.args
                if isinstance(a, Tensor)
                and getattr(a, "_is_feed", False)}
    dep = set(in_ids) | feed_ids
    sub = []
    for node in nodes:
        if any(isinstance(a, Tensor) and id(a) in dep for a in node.args):
            sub.append(node)
            dep.update(id(o) for o in node.outputs)
    need = set(out_ids)
    keep = []
    for node in reversed(sub):
        if any(id(o) in need for o in node.outputs):
            keep.append(node)
            need.update(id(a) for a in node.args
                        if isinstance(a, Tensor))
    keep.reverse()
    for node in keep:
        if "__pylayer__" in node.kwargs:
            raise NotImplementedError(
                "paddle.grad(create_graph=True) through a PyLayer is "
                "not supported; express the custom backward with "
                "jax-differentiable ops or take the outer grad with "
                "paddle.incubate.autograd functional transforms")

    unused = [i for i, t in enumerate(inputs) if id(t) not in need
              and id(t) not in out_ids]
    if unused and not allow_unused:
        raise RuntimeError(
            "One of the differentiated tensors appears unused; "
            "pass allow_unused=True to return None for it.")

    for node in keep:
        for t in list(node.outputs) + [a for a in node.args
                                       if isinstance(a, Tensor)]:
            if _has_hooks(t):
                raise NotImplementedError(
                    "paddle.grad(create_graph=True) does not run "
                    "Tensor.register_hook hooks (the subgraph is "
                    "replayed under jax.vjp, outside the eager walk "
                    "that fires them); remove the hook or use "
                    "create_graph=False")

    # the env is id-keyed, so duplicate `inputs` entries must collapse
    # to ONE closure argument — each duplicate position then receives
    # the full gradient (matching the eager path's per-position reads)
    uniq_inputs, uniq_ids, pos_to_uniq = [], [], []
    for t in inputs:
        if id(t) not in uniq_ids:
            uniq_ids.append(id(t))
            uniq_inputs.append(t)
        pos_to_uniq.append(uniq_ids.index(id(t)))

    # every required-grad LEAF the subgraph reads (parameters, other
    # tape-external tensors) must be a differentiable argument of the
    # recorded closure, not a baked-in constant — otherwise the outer
    # backward of the returned grads cannot reach them
    # (d(grad-penalty)/dθ).  Tensors PRODUCED by kept nodes are
    # recomputed inside the replay and never read from env — keeping
    # them out avoids dead closure arguments.
    produced = {id(o) for node in keep for o in node.outputs}
    extra, seen = [], set(uniq_ids)
    for node in keep:
        for a in node.args:
            if (isinstance(a, Tensor)
                    and id(a) not in seen and id(a) not in produced
                    # static-mode FEED placeholders must be closure
                    # args even though they don't require grad: the
                    # Executor substitutes the fed value at replay —
                    # baking the placeholder in would differentiate at
                    # the wrong point
                    and (not a.stop_gradient
                         or getattr(a, "_is_feed", False))):
                seen.add(id(a))
                extra.append(a)
    # grad_outputs that are required-grad Tensors are part of the graph
    # (g = seed * dy/dx): they must be closure arguments too, or the
    # outer backward misses the d(seed)/d(...) * dy/dx term
    for go in grad_outputs:
        if isinstance(go, Tensor) and not go.stop_gradient:
            if id(go) in produced:
                raise NotImplementedError(
                    "paddle.grad(create_graph=True): a grad_outputs "
                    "tensor produced INSIDE the differentiated "
                    "subgraph would need its dependence replayed "
                    "jointly; detach it or restructure the objective")
            if id(go) not in seen:
                seen.add(id(go))
                extra.append(go)
    all_diff = uniq_inputs + extra
    n_in = len(uniq_inputs)
    id_to_slot = {id(t): j for j, t in enumerate(all_diff)}

    def f(*vals):
        env = {id(t): v for t, v in zip(all_diff, vals)}
        for node in keep:
            nvals = []
            for a, rec in zip(node.args, node.arg_vals):
                if isinstance(a, Tensor) and id(a) in env:
                    v = env[id(a)]
                    # recorded arg_vals may be amp-cast copies
                    if getattr(v, "dtype", None) is not None and \
                            getattr(rec, "dtype", None) is not None \
                            and v.dtype != rec.dtype:
                        v = v.astype(rec.dtype)
                    nvals.append(v)
                else:
                    nvals.append(rec)
            outs = node.fn(*nvals, **node.kwargs)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for o, ov in zip(node.outputs, outs):
                env[id(o)] = ov
        return tuple(env.get(oid, t._value)
                     for oid, t in zip(out_ids, outputs))

    def g(*vals):
        rest = vals[n_in:]
        seeds = []
        for t, go in zip(outputs, grad_outputs):
            if isinstance(go, Tensor) and id(go) in id_to_slot:
                sv = vals[id_to_slot[id(go)]]
            elif go is None:
                sv = _ones_like(t._value)
            else:
                sv = go._value if hasattr(go, "_value") \
                    else jnp.asarray(go)
            seeds.append(_ct_like(sv, t))
        _, vjp_fn = jax.vjp(
            lambda *iv: f(*iv, *rest), *vals[:n_in])
        return vjp_fn(tuple(seeds))

    outs = apply_closure(g, all_diff, name="grad")
    outs = outs if isinstance(outs, tuple) else (outs,)
    return [None if i in unused else outs[pos_to_uniq[i]]
            for i in range(len(inputs))]


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """``paddle.grad`` — returns grads of ``outputs`` w.r.t. ``inputs``
    without touching ``.grad`` slots.  Implemented by running the normal
    tape walk into a private accumulator; ``create_graph=True`` instead
    replays the subgraph under ``jax.vjp`` and records the grads as
    tape outputs so they are differentiable again (double grad)."""
    from ..tensor import Tensor
    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused)

    cts: Dict[int, Any] = {}
    for t, g in zip(outputs, grad_outputs):
        seed = _ones_like(t._value) if g is None else (
            g._value if hasattr(g, "_value") else jnp.asarray(g))
        _accum(cts, id(t), seed)

    hook_done: set = set()
    for node in reversed(_tape):
        _finalize_hooked_outputs(node, cts, hook_done, {})
        in_cts = _node_vjp(node, cts)
        if in_cts is None:
            continue
        for i, ct in zip(node.diff_idx, in_cts):
            if ct is not None and not node.args[i].stop_gradient:
                _accum(cts, id(node.args[i]), ct)

    results = []
    for t in inputs:
        c = cts.get(id(t))
        if c is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "pass allow_unused=True to return None for it.")
            results.append(None)
        else:
            from ..framework.selected_rows import SelectedRows
            if isinstance(c, SelectedRows):
                # paddle.grad returns dense tensors; sparse stays on the
                # .grad attribute path only
                c = c.to_dense()
            results.append(Tensor(c, stop_gradient=not create_graph))
    if retain_graph is False or retain_graph is None and not create_graph:
        pass  # keep tape: paddle.grad defaults to retaining for repeat calls
    return results
