"""User-facing autograd (parity: python/paddle/autograd/)."""

from .tape import (  # noqa
    backward, grad, no_grad, enable_grad, is_grad_enabled,
    set_grad_enabled, reset_tape)
from .py_layer import PyLayer, PyLayerContext  # noqa
from .functional import (  # noqa
    jvp, vjp, jacobian, hessian, Jacobian, Hessian)
