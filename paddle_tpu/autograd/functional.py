"""Functional higher-order autograd (parity:
python/paddle/incubate/autograd/ — jvp, vjp, Jacobian, Hessian — and
the 2.6-era functional ``paddle.autograd.jacobian/hessian``).

TPU-native: these ARE jax's transforms.  The user function is lifted
to a pure jax function (Tensor wrappers in, Tensor wrappers out, eager
tape suppressed inside) and handed to ``jax.jvp`` / ``jax.vjp`` /
``jax.jacfwd`` / ``jax.jacrev`` — forward-over-reverse for the
Hessian, the composition upstream implements by stacking its prim
rules.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

__all__ = ["jvp", "vjp", "jacobian", "hessian", "Jacobian", "Hessian"]


def _values(xs):
    from ..tensor import Tensor
    if isinstance(xs, (list, tuple)):
        return [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                for x in xs]
    return [xs._value if isinstance(xs, Tensor) else jnp.asarray(xs)]


def _is_seq(xs) -> bool:
    return isinstance(xs, (list, tuple))


def _pure(func: Callable, n: int, seq_in: bool):
    """Wrap a Tensor-level callable as a pure jax fn of n arrays."""

    def fn(*vals):
        from ..tensor import Tensor
        from . import tape as _tape
        with _tape.no_grad_ctx():
            args = [Tensor(v) for v in vals]
            out = func(*args) if (seq_in or n > 1) else func(args[0])
        if isinstance(out, (list, tuple)):
            return tuple(o._value for o in out)
        return out._value

    return fn


def _rewrap_like(vals, like_seq: bool):
    from ..tensor import Tensor
    outs = tuple(Tensor(v, stop_gradient=True) for v in vals)
    return outs if like_seq or len(outs) != 1 else outs[0]


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns ``(func(xs), J @ v)`` (upstream
    incubate.autograd.jvp).  ``v`` defaults to ones like ``xs``."""
    seq = _is_seq(xs)
    vals = _values(xs)
    if v is None:
        tans = [jnp.ones_like(a) for a in vals]
    else:
        tans = _values(v)
    fn = _pure(func, len(vals), seq)
    out, tangent = jax.jvp(fn, tuple(vals), tuple(tans))
    multi_out = isinstance(out, tuple)
    outs = out if multi_out else (out,)
    tangents = tangent if multi_out else (tangent,)
    return (_rewrap_like(outs, multi_out),
            _rewrap_like(tangents, multi_out))


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: returns ``(func(xs), vᵀ @ J)`` (upstream
    incubate.autograd.vjp)."""
    seq = _is_seq(xs)
    vals = _values(xs)
    fn = _pure(func, len(vals), seq)
    out, pullback = jax.vjp(fn, *vals)
    multi_out = isinstance(out, tuple)
    if v is None:
        cts = tuple(jnp.ones_like(o)
                    for o in (out if multi_out else (out,)))
        cts = cts if multi_out else cts[0]
    else:
        cvals = _values(v)
        cts = tuple(cvals) if multi_out else cvals[0]
    grads = pullback(cts)
    outs = out if multi_out else (out,)
    return (_rewrap_like(outs, multi_out),
            _rewrap_like(grads, seq))


def jacobian(func: Callable, xs, batch_axis=None) -> Union[Tensor, tuple]:
    """Full Jacobian of ``func`` at ``xs`` via jacrev (upstream
    paddle.autograd.jacobian functional form).

    For scalar-to-tensor or tensor-to-tensor ``func``; with
    ``batch_axis=0`` the leading dim is treated as batch (a jax vmap
    over per-example jacrev)."""
    seq = _is_seq(xs)
    vals = _values(xs)
    fn = _pure(func, len(vals), seq)
    argnums = tuple(range(len(vals)))
    if batch_axis is None:
        jac = jax.jacrev(fn, argnums=argnums)(*vals)
    elif batch_axis == 0:
        jac = jax.vmap(jax.jacrev(fn, argnums=argnums))(*vals)
    else:
        raise ValueError("batch_axis must be None or 0")
    # jac: per-output (if multi) × per-input pytree of arrays
    from ..tensor import Tensor

    def wrap(j):
        if isinstance(j, tuple):
            return tuple(wrap(x) for x in j)
        return Tensor(j, stop_gradient=True)
    out = wrap(jac)
    if not seq and isinstance(out, tuple) and len(out) == 1:
        return out[0]
    return out


def hessian(func: Callable, xs, batch_axis=None):
    """Hessian of a SCALAR-output ``func`` — forward-over-reverse
    (jacfwd∘jacrev), the efficient composition on TPU."""
    seq = _is_seq(xs)
    vals = _values(xs)
    fn = _pure(func, len(vals), seq)
    argnums = tuple(range(len(vals)))

    def scalar_fn(*a):
        out = fn(*a)
        if isinstance(out, tuple):
            raise ValueError("hessian expects a single scalar output")
        return jnp.reshape(out, ())

    hess_fn = jax.jacfwd(jax.jacrev(scalar_fn, argnums=argnums),
                         argnums=argnums)
    if batch_axis is None:
        h = hess_fn(*vals)
    elif batch_axis == 0:
        h = jax.vmap(hess_fn)(*vals)
    else:
        raise ValueError("batch_axis must be None or 0")

    from ..tensor import Tensor

    def wrap(j):
        if isinstance(j, tuple):
            return tuple(wrap(x) for x in j)
        return Tensor(j, stop_gradient=True)
    out = wrap(h)
    if not seq and isinstance(out, tuple) and len(out) == 1:
        inner = out[0]
        if isinstance(inner, tuple) and len(inner) == 1:
            return inner[0]
        return inner
    return out


class Jacobian:
    """Lazy Jacobian object (upstream paddle.autograd.Jacobian): index
    ``J[i, j]`` or materialise via ``paddle.autograd.jacobian``."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._mat = jacobian(func, xs,
                             batch_axis=0 if is_batched else None)

    def __getitem__(self, idx):
        from ..tensor import Tensor
        m = self._mat
        if isinstance(m, tuple):
            raise TypeError("indexing a multi-input Jacobian; select "
                            "the input first via .tensors")
        return Tensor(m._value[idx], stop_gradient=True)

    @property
    def tensors(self):
        return self._mat

    @property
    def shape(self):
        from ..tensor import Tensor
        m = self._mat
        return m.shape if isinstance(m, Tensor) else \
            [t.shape for t in m]


class Hessian(Jacobian):
    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._mat = hessian(func, xs,
                            batch_axis=0 if is_batched else None)
