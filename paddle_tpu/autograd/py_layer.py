"""PyLayer: user-defined autograd ops in Python.

Parity: python/paddle/autograd/py_layer.py — users subclass ``PyLayer``
with static ``forward``/``backward``; backward receives upstream grads
and returns grads for forward's tensor inputs.  Implemented by recording
a single closure tape node whose "jax function" is a ``jax.custom_vjp``
wrapping the user's two staticmethods, so it composes with the rest of
the tape exactly like a built-in op (the analog of upstream's
``PyLayerGradNode``).
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp

from . import tape as _tape


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.non_differentiable = []

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_non_differentiable(self, *tensors):
        self.non_differentiable.extend(tensors)


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..tensor import Tensor
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]

        # Run forward with grad disabled — the op is atomic on the tape.
        with _tape.no_grad_ctx():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]

        any_grad = any(not t.stop_gradient for t in tensor_args) \
            and _tape.is_grad_enabled()
        if any_grad:
            diff_idx = [i for i, t in enumerate(tensor_args)
                        if not t.stop_gradient]

            def _fn(*vals):
                # Forward value already computed; this function exists so
                # the tape can call jax.vjp on it.  We wrap the user's
                # backward as a custom VJP to avoid re-differentiating the
                # (possibly non-traceable) forward.
                raise RuntimeError("PyLayer forward should not be re-run")

            # Record a special node; backward dispatch is custom.
            node = _tape.TapeNode(_fn, tuple(tensor_args),
                                  tuple(t._value for t in tensor_args),
                                  {}, tuple(diff_idx), tuple(out_list),
                                  cls.__name__)
            node.fn = None  # flag: custom node
            node.kwargs = {"__pylayer__": (cls, ctx, len(tensor_args))}
            _tape._tape.append(node)
            for o in out_list:
                if o not in ctx.non_differentiable and jnp.issubdtype(
                        o._value.dtype, jnp.inexact):
                    o.stop_gradient = False
        return outs


def _pylayer_vjp(node, out_cts_full):
    """Dispatch a PyLayer node's backward: call the user's backward with
    upstream grads as Tensors; returns cotangent arrays per diff input."""
    from ..tensor import Tensor
    cls, ctx, n_in = node.kwargs["__pylayer__"]
    grads_in = [Tensor(c) if c is not None else None for c in out_cts_full]
    with _tape.no_grad_ctx():
        res = cls.backward(ctx, *grads_in)
    if not isinstance(res, (tuple, list)):
        res = (res,)
    out = []
    for i in node.diff_idx:
        r = res[i] if i < len(res) else None
        out.append(None if r is None else
                   (r._value if isinstance(r, Tensor) else jnp.asarray(r)))
    return out
