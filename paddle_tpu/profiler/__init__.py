"""paddle.profiler facade (parity: python/paddle/profiler/ —
SURVEY.md §5.1), re-backed onto the unified observability recorder
(DESIGN-OBSERVABILITY.md).

Device side: jax.profiler → XPlane/TensorBoard (replacing CUPTI).
Host side: ``Profiler`` start/stop arm :mod:`paddle_tpu.observability
.trace` — the SAME ring buffer the dispatch engine, fit loop, mesh
runner, serving engine and checkpoint IO record into — so a profiled
run exports ONE timeline carrying both user ``RecordEvent``
annotations and the framework's own spans.  ``export_chrome_tracing``
dumps that unified timeline.  ``RecordEvent`` additionally feeds the
native C++ tracer (paddle_tpu/native/src/host_tracer.cc) when it is
armed, keeping the pre-existing native export path alive."""

from __future__ import annotations

import contextlib
import enum
import os
import time
from typing import Callable, Iterable, Optional

import jax

from ..native import host_tracer as _host_tracer
from ..observability import trace as _obs_trace


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed: int = 0, ready: int = 0, record: int = 1,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    total = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready handler writing the UNIFIED chrome trace — the
    observability recorder's timeline, which carries the profiled
    run's ``RecordEvent`` annotations alongside the framework's own
    dispatch/fit/serving/checkpoint spans on one clock."""
    def handler(prof):
        prof._log_dir = dir_name
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        _obs_trace.dump_chrome_trace(
            os.path.join(dir_name, f"{name}.json"))
    return handler


class Profiler:
    def __init__(self, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready=None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory=False,
                 with_flops: bool = False):
        self._timer_only = timer_only
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._log_dir = os.environ.get("PADDLE_PROFILER_LOGDIR",
                                       "./profiler_log")
        self._step = 0
        self._active = False
        self._step_times = []
        self._last_ts = None
        self._armed_recorder = False

    def start(self):
        if not self._timer_only:
            # delegate the host timeline to the unified recorder: the
            # profiled window records into the SAME ring as the
            # framework's own instrumentation (one timeline, ISSUE 8).
            # Remember whether WE armed it so stop() doesn't disable a
            # recorder the user armed via PADDLE_TPU_TRACE.
            self._armed_recorder = not _obs_trace.enabled()
            if self._armed_recorder:
                # fresh window when WE arm: back-to-back profiler
                # sessions must not leak spans into each other's
                # export (parity with the native tracer, which
                # cleared its buffer on every enable)
                _obs_trace.clear()
            _obs_trace.enable()
            _host_tracer.enable()
            try:
                jax.profiler.start_trace(self._log_dir)
                self._active = True
            except Exception:
                self._active = False
        self._last_ts = time.perf_counter()

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        _host_tracer.disable()
        if self._armed_recorder:
            # stop recording but KEEP the ring: export and summary()
            # read the profiled window after stop()
            _obs_trace.disable()
            self._armed_recorder = False

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_ts is not None:
            self._step_times.append(now - self._last_ts)
        if _obs_trace.enabled():
            _obs_trace.instant("profiler.step",
                               args={"step": self._step})
        self._last_ts = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        avg = sum(self._step_times) / len(self._step_times)
        return f"avg step time {avg * 1000:.2f} ms"

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Print step timing + host-span table (upstream: op/kernel
        summary tables) aggregated from the unified recorder's
        timeline, merged with any spans the native tracer still
        holds."""
        print(self.step_info())
        stats = dict(_obs_trace.summary())
        for name, s in host_span_stats().items():
            if name not in stats:
                stats[name] = s
        if not stats:
            return
        name_w = max(len(n) for n in stats) + 2
        print(f"{'Name':<{name_w}}{'Calls':>8}{'Total(ms)':>12}"
              f"{'Avg(ms)':>10}{'Max(ms)':>10}{'Ratio%':>8}")
        total_all = sum(s['total'] for s in stats.values()) or 1.0
        order = sorted(stats.items(), key=lambda kv: -kv[1]["total"])
        for name, s in order:
            print(f"{name:<{name_w}}{s['count']:>8}"
                  f"{s['total']:>12.3f}{s['avg']:>10.3f}"
                  f"{s['max']:>10.3f}"
                  f"{100.0 * s['total'] / total_all:>8.1f}")

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def host_span_stats():
    """Aggregate the native tracer's span buffer into per-name stats
    (count/total/avg/max in ms)."""
    import json
    import tempfile
    if _host_tracer.count() == 0:
        return {}
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        path = f.name
    try:
        if not _host_tracer.dump(path):
            return {}
        with open(path) as fh:
            events = json.load(fh)["traceEvents"]
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    stats = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        s = stats.setdefault(e["name"],
                             {"count": 0, "total": 0.0, "max": 0.0})
        dur_ms = e["dur"] / 1000.0
        s["count"] += 1
        s["total"] += dur_ms
        s["max"] = max(s["max"], dur_ms)
    for s in stats.values():
        s["avg"] = s["total"] / s["count"]
    return stats


class RecordEvent:
    """Host-side trace annotation: spans go to the unified
    observability recorder (the ONE timeline, when armed), the native
    host tracer (when enabled), and jax.profiler.TraceAnnotation
    (XPlane correlation)."""

    def __init__(self, name: str, event_type=None):
        self._name = name
        self._ctx = None
        self._native = False
        self._uspan = None

    def begin(self):
        # begin() twice without end() would overwrite (and leak) the
        # previous span/annotation window — close it first
        if self._uspan is not None or self._ctx is not None:
            self.end()
        self._uspan = _obs_trace.span(self._name)
        self._uspan.__enter__()
        if _host_tracer.enabled():
            _host_tracer.begin(self._name)
            self._native = True
        self._ctx = jax.profiler.TraceAnnotation(self._name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        if self._native:
            _host_tracer.end()
            self._native = False
        if self._uspan is not None:
            self._uspan.__exit__(None, None, None)
            self._uspan = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(path):
    raise NotImplementedError("load_profiler_result: use TensorBoard on "
                              "the XPlane trace directory")
