"""paddle.inference — deploy-a-saved-model predictor API.

Parity: upstream ``paddle/fluid/inference/`` (`AnalysisPredictor`,
`paddle_inference_api.h`) and its Python surface
``paddle.inference.Config`` / ``create_predictor`` — the contract a
PaddleDetection/PaddleOCR deployment script uses:

    config = Config(model_file, params_file)
    predictor = create_predictor(config)
    names = predictor.get_input_names()
    h = predictor.get_input_handle(names[0])
    h.reshape(shape); h.copy_from_cpu(np_array)
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0])
    result = out.copy_to_cpu()

Upstream runs a ProgramDesc through ~200 IR fuse passes and optional
TensorRT subgraphs.  The TPU-native model format is the ``jax.export``
StableHLO artifact written by ``paddle.jit.save`` (.pdmodel +
.pdiparams + .pdmeta); "IR optimization" is XLA's job at compile time,
so `switch_ir_optim`/`enable_memory_optim` are accepted no-op knobs
(recorded on the config for `summary()`).  Programs exported with
symbolic (dynamic) dims execute at any concrete shape; fixed-shape
exports enforce their shape like upstream does.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import flatten as _flatten

__all__ = [
    "Config", "Predictor", "Tensor", "create_predictor",
    "PrecisionType", "PlaceType", "get_version", "serving",
]


def __getattr__(name):
    # `paddle.inference.serving` loads lazily: the serving subsystem
    # pulls in io/framework modules that may still be mid-import when
    # the package initializes, and offline Predictor users never pay
    # for the server stack
    if name == "serving":
        import importlib
        mod = importlib.import_module(".serving", __name__)
        globals()["serving"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"       # accepted for script compat; maps to the default
    XPU = "xpu"       # jax backend (TPU when present)
    CUSTOM = "custom"
    TPU = "tpu"


def get_version() -> str:
    from .. import __version__
    return f"paddle_tpu inference {__version__}"


class Config:
    """Mirror of ``paddle.inference.Config``.

    Accepts either ``Config(prog_file, params_file)`` (upstream
    two-file form — ``prog_file`` may be the ``.pdmodel`` path or the
    ``jit.save`` prefix) or ``Config(model_dir)`` where the directory
    contains exactly one ``*.pdmodel``.
    """

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._prefix = None
        if prog_file is not None and params_file is None \
                and os.path.isdir(prog_file):
            cands = [f for f in sorted(os.listdir(prog_file))
                     if f.endswith(".pdmodel")]
            if len(cands) != 1:
                raise ValueError(
                    f"Config(model_dir): expected exactly one .pdmodel "
                    f"in {prog_file!r}, found {cands}")
            self._prefix = os.path.join(prog_file, cands[0][:-len(".pdmodel")])
        elif prog_file is not None:
            p = prog_file
            if p.endswith(".pdmodel"):
                p = p[:-len(".pdmodel")]
            self._prefix = p
            if params_file is not None:
                want = self._prefix + ".pdiparams"
                if os.path.abspath(params_file) != os.path.abspath(want):
                    raise ValueError(
                        f"params_file {params_file!r} does not match the "
                        f"model prefix (expected {want!r}); the TPU-native "
                        "format keeps the jit.save prefix convention")
        # accepted-for-compat knobs, recorded for summary()
        self._use_device = PlaceType.TPU
        self._device_id = 0
        self._ir_optim = True
        self._memory_optim = False
        self._precision = PrecisionType.Float32
        self._enable_profile = False

    # --- upstream knob surface (device selection is advisory: jax owns
    #     placement; these keep deployment scripts running unchanged) ---
    def set_model(self, prog_file: str, params_file: str = None) -> None:
        # upstream set_model only changes the paths — configured knobs
        # (device, precision, ir/memory optim) must survive, and a
        # failed path validation must leave the config untouched
        saved = dict(self.__dict__)
        try:
            self.__init__(prog_file, params_file)
        except Exception:
            self.__dict__.update(saved)
            raise
        prefix = self._prefix
        self.__dict__.update(saved)
        self._prefix = prefix

    def model_dir(self) -> str:
        return os.path.dirname(self._prefix) if self._prefix else ""

    def prog_file(self) -> str:
        return (self._prefix + ".pdmodel") if self._prefix else ""

    def params_file(self) -> str:
        return (self._prefix + ".pdiparams") if self._prefix else ""

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0, precision=None) -> None:
        self._use_device = PlaceType.GPU
        self._device_id = device_id
        if precision is not None:
            self._precision = precision

    def disable_gpu(self) -> None:
        self._use_device = PlaceType.CPU

    def use_gpu(self) -> bool:
        return self._use_device == PlaceType.GPU

    def enable_xpu(self, *a, **kw) -> None:
        self._use_device = PlaceType.XPU

    def switch_ir_optim(self, x: bool = True) -> None:
        self._ir_optim = bool(x)

    def ir_optim(self) -> bool:
        return self._ir_optim

    def enable_memory_optim(self, x: bool = True) -> None:
        self._memory_optim = bool(x)

    def enable_profile(self) -> None:
        self._enable_profile = True

    def switch_use_feed_fetch_ops(self, x: bool = False) -> None:
        pass

    def switch_specify_input_names(self, x: bool = True) -> None:
        pass

    def set_cpu_math_library_num_threads(self, n: int) -> None:
        pass

    def enable_tensorrt_engine(self, *a, **kw) -> None:
        raise NotImplementedError(
            "TensorRT subgraphs are CUDA-only upstream machinery; the "
            "TPU-native predictor compiles the whole program with XLA. "
            "Remove enable_tensorrt_engine() from the deployment script.")

    def summary(self) -> str:
        rows = [
            ("model file", self.prog_file()),
            ("params file", self.params_file()),
            ("device", f"{self._use_device}:{self._device_id}"),
            ("ir_optim (XLA)", str(self._ir_optim)),
            ("memory_optim", str(self._memory_optim)),
            ("precision", str(self._precision)),
        ]
        w = max(len(k) for k, _ in rows)
        return "\n".join(f"{k.ljust(w)}  {v}" for k, v in rows)


class Tensor:
    """I/O handle (upstream ``paddle.inference.Tensor`` /
    ``ZeroCopyTensor``): host-side staging + copy_to/from_cpu."""

    def __init__(self, name: str, is_input: bool,
                 spec: Optional[tuple] = None):
        self._name = name
        self._is_input = is_input
        self._spec = spec          # (shape-with-None, dtype-str) | None
        self._host: Optional[np.ndarray] = None
        self._dev = None           # output-side jax array

    def name(self) -> str:
        return self._name

    def reshape(self, shape: Sequence[int]) -> None:
        if not self._is_input:
            raise RuntimeError("reshape() is only valid on input handles")
        dt = self._spec[1] if self._spec else "float32"
        if self._host is not None and \
                int(np.prod(shape)) == self._host.size:
            self._host = np.ascontiguousarray(self._host).reshape(shape)
        else:
            self._host = np.zeros(tuple(int(s) for s in shape), dtype=dt)

    def copy_from_cpu(self, data: np.ndarray) -> None:
        if not self._is_input:
            raise RuntimeError(
                "copy_from_cpu() is only valid on input handles")
        data = np.asarray(data)
        if self._spec:
            want, dt = self._spec
            if len(want) != data.ndim or any(
                    w is not None and int(w) != int(g)
                    for w, g in zip(want, data.shape)):
                raise ValueError(
                    f"input {self._name!r}: shape {tuple(data.shape)} "
                    f"does not match exported spec {tuple(want)} "
                    "(None = dynamic)")
            data = data.astype(dt, copy=True)
        else:
            # upstream ZeroCopyTensor copies into its own buffer — the
            # caller may mutate/reuse the source array after this call
            data = data.copy()
        self._host = data

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError(
                "copy_to_cpu() is only valid on output handles")
        if self._dev is None:
            raise RuntimeError("run() has not produced this output yet")
        return np.asarray(self._dev)

    def shape(self) -> List[int]:
        src = self._host if self._is_input else self._dev
        if src is None:
            return [(-1 if s is None else int(s)) for s in self._spec[0]] \
                if self._spec else []
        return list(src.shape)

    def type(self):
        src = self._host if self._is_input else self._dev
        if src is not None:
            return np.dtype(src.dtype)
        return np.dtype(self._spec[1]) if self._spec else np.float32


class Predictor:
    """Executes a ``paddle.jit.save`` artifact (upstream
    ``AnalysisPredictor``).  The exported StableHLO program is
    deserialized once; clones share it (upstream's
    ``predictor.clone()`` shares the optimized program the same way).
    """

    def __init__(self, config: Config, _shared=None):
        self._config = config
        if _shared is not None:
            self._call, self._params, self._specs, self._n_out = _shared
        else:
            prefix = config._prefix
            if not prefix:
                raise ValueError("Config has no model path")
            from ..jit.save_load import load as _jit_load
            tl = _jit_load(prefix)
            if tl._exported_fn is None:
                err = tl._meta.get("export_error", "saved without input_spec")
                raise RuntimeError(
                    f"{prefix}.pdmodel has no executable program ({err}); "
                    "re-export with paddle.jit.save(layer, path, "
                    "input_spec=[...])")
            self._call = tl._exported_fn
            self._params = tl._params
            self._specs = [
                (tuple(None if (d is None or (isinstance(d, int) and d < 0))
                       else int(d) for d in shp), dt)
                for shp, dt in tl._meta.get("input_spec", [])]
            self._n_out = len(tl._exported.out_avals)
        self._inputs = [Tensor(f"x{i}", True, spec)
                        for i, spec in enumerate(self._specs)]
        # handles are created ONCE and stay valid across run() calls —
        # deployment loops cache them at setup (upstream contract)
        self._outputs: List[Tensor] = [Tensor(f"out{i}", False)
                                       for i in range(self._n_out)]

    def get_input_names(self) -> List[str]:
        return [t.name() for t in self._inputs]

    def get_input_handle(self, name: str) -> Tensor:
        for t in self._inputs:
            if t.name() == name:
                return t
        raise KeyError(f"no input named {name!r}; "
                       f"inputs are {self.get_input_names()}")

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute.  Upstream signature: handles are filled beforehand
        and ``run()`` takes no args; the list form (newer upstream
        ``predictor.run([x, ...])``) is also supported and returns the
        outputs directly."""
        if inputs is not None:
            if len(inputs) != len(self._inputs):
                raise ValueError(
                    f"run() got {len(inputs)} inputs but the program "
                    f"takes {len(self._inputs)} "
                    f"({self.get_input_names()})")
            for h, x in zip(self._inputs, inputs):
                h.copy_from_cpu(np.asarray(
                    x.numpy() if hasattr(x, "numpy") else x))
        feed = []
        for h in self._inputs:
            if h._host is None:
                raise RuntimeError(
                    f"input {h.name()!r} was never fed; call "
                    "copy_from_cpu() on every input handle before run()")
            feed.append(h._host)
        out = self._call(self._params, *feed)
        flat = _flatten(out)
        # update cached handles in place — handle identity is stable
        for t, o in zip(self._outputs, flat):
            t._dev = o
        if inputs is not None:
            return [t.copy_to_cpu() for t in self._outputs]
        return True

    def get_output_names(self) -> List[str]:
        return [t.name() for t in self._outputs]

    def get_output_handle(self, name: str) -> Tensor:
        for t in self._outputs:
            if t.name() == name:
                return t
        raise KeyError(f"no output named {name!r}; outputs are "
                       f"{self.get_output_names()}")

    def clone(self) -> "Predictor":
        return Predictor(self._config,
                         _shared=(self._call, self._params, self._specs,
                                  self._n_out))

    def clear_intermediate_tensor(self) -> None:
        pass

    def try_shrink_memory(self) -> None:
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
