"""Shared-prefix KV cache: block-granular prefill reuse with refcounts
(DESIGN-SERVING.md §Long-context tier).

Production serving traffic is system-prompt dominated: thousands of
requests open with the same instruction block, and recomputing its
K/V per request burns exactly the FLOPs a paged cache exists to keep.
This module hashes prompt prefixes to the pool blocks that already
hold their K/V, on top of the ``BlockAllocator``'s per-block
accounting:

- **Chain hashing at block granularity.**  A prompt's full blocks
  (``block_size`` tokens each) hash as a chain — entry ``i``'s key is
  ``sha256(key[i-1] || tokens[i*BS:(i+1)*BS])`` — so a hit at depth
  ``n`` certifies the *entire* ``n*BS``-token prefix matches, not just
  one block.  Absolute positions are implicit: chain depth IS the
  block's position, and identical tokens at identical positions
  produce identical K/V (position embeddings included), which is what
  makes reuse exact.
- **Ownership + refcounts.**  A cached block is owned by the cache;
  live requests whose page tables include it hold a reference.  A
  request's *exclusive* blocks (partial prompt tail, generated
  tokens) never enter the cache and free at finalize exactly as
  before.  ``refs == 0`` means "no live table points here" — the
  entry is idle, kept warm for the next hit, and evictable.
- **Leaf-first LRU eviction under pressure.**  The admission
  invariant (sum of worst-case reservations <= capacity, reservations
  deliberately NOT discounted by expected hits) guarantees that
  live-request needs always fit; idle cached blocks are the only
  overflow, and ``ensure_free`` reclaims them least-recently-used
  first, leaves before parents, so a surviving chain never has a hole
  (a hole would strand unreachable deeper entries: ``match`` walks
  from depth 0 and stops at the first miss).

The engine's single pump thread owns every call here — no locking,
same threading contract as the allocator it sits on.
"""

from __future__ import annotations

import hashlib
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from .kv_cache import BlockAllocator, OutOfBlocks


class PrefixEntry:
    """One cached block: chain key, pool block id, live references."""

    __slots__ = ("key", "parent", "block", "refs", "last_used",
                 "children")

    def __init__(self, key: bytes, parent: Optional[bytes], block: int):
        self.key = key
        self.parent = parent
        self.block = int(block)
        self.refs = 0
        self.last_used = 0
        self.children = 0        # cached (not live) child entries

    def __repr__(self):
        return (f"PrefixEntry(block={self.block}, refs={self.refs}, "
                f"children={self.children})")


def _chain_hash(prev: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.sha256(prev)
    h.update(b"|".join(str(int(t)).encode() for t in tokens))
    return h.digest()


class PrefixCache:
    """Prefix → pool-block map with refcounts and LRU eviction.

    ``pin_referenced=True`` arms the reservation-discount admission
    mode (DESIGN-SERVING.md §Disaggregated tier): every entry whose
    refcount rises 0→1 pins one block on the allocator (falls 1→0
    unpins), so live-referenced cache blocks — occupied, un-evictable,
    and NOT covered by any discounted reservation — still count in
    the admission envelope.  Off (the default), admission reserves
    the full worst case and the envelope never needs the pin.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int,
                 pin_referenced: bool = False):
        self._alloc = allocator
        self.block_size = int(block_size)
        self.pin_referenced = bool(pin_referenced)
        self._entries: Dict[bytes, PrefixEntry] = {}
        self._tick = itertools.count(1)
        # lifetime stats (the engine mirrors them onto the registry)
        self.hits = 0            # blocks reused from cache
        self.misses = 0          # shareable blocks computed fresh
        self.evictions = 0       # idle entries reclaimed

    def _ref(self, e: PrefixEntry):
        e.refs += 1
        if e.refs == 1 and self.pin_referenced:
            self._alloc.pin(1)

    # -- introspection -------------------------------------------------------
    @property
    def cached_blocks(self) -> int:
        return len(self._entries)

    @property
    def live_refs(self) -> int:
        return sum(e.refs for e in self._entries.values())

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {"cached_blocks": self.cached_blocks,
                "live_refs": self.live_refs,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else 0.0}

    # -- lookup / acquire ----------------------------------------------------
    def shareable_blocks(self, prompt: Sequence[int]) -> int:
        """How many leading blocks of this prompt are share-eligible:
        full blocks only, and never the whole prompt — at least one
        suffix token must run through prefill so the request's first
        generated token has logits to come from."""
        return max(0, (len(prompt) - 1) // self.block_size)

    def match(self, prompt: Sequence[int], count: bool = True
              ) -> Tuple[List[PrefixEntry], bytes]:
        """Longest cached prefix of ``prompt``: returns the matched
        entries (a reference is taken on each — pair with
        :meth:`release`) and the chain hash at the match depth, which
        :meth:`insert` extends for the blocks this request computes
        itself.  Counts hits (matched) and misses (share-eligible but
        absent) on the lifetime stats; ``count=False`` defers that to
        an explicit :meth:`count_match` — the discounted-admission
        path matches speculatively at every reservation attempt and
        must not inflate the rate while a request waits at the
        door."""
        bs = self.block_size
        n = self.shareable_blocks(prompt)
        got: List[PrefixEntry] = []
        h = b""
        for i in range(n):
            nxt = _chain_hash(h, prompt[i * bs:(i + 1) * bs])
            e = self._entries.get(nxt)
            if e is None:
                break
            h = nxt
            got.append(e)
        tick = next(self._tick)
        for e in got:
            self._ref(e)
            e.last_used = tick
        if count:
            self.count_match(len(got), n - len(got))
        return got, h

    def count_match(self, hits: int, misses: int):
        """Fold one ADMITTED request's match outcome into the lifetime
        hit/miss stats (see ``match(count=False)``)."""
        self.hits += int(hits)
        self.misses += int(misses)

    # -- insert / release ----------------------------------------------------
    def insert(self, prompt: Sequence[int], start_block: int,
               chain_hash: bytes, blocks: Sequence[int]
               ) -> Tuple[List[PrefixEntry], List[int]]:
        """Register freshly prefilled full blocks, transferring their
        ownership to the cache (the caller keeps a reference on each
        new entry).  ``start_block``/``chain_hash`` come from
        :meth:`match`; ``blocks`` are the pool ids holding blocks
        ``start_block..`` of the prompt.  Returns ``(entries,
        leftover)``: entries the caller now references, and block ids
        that stay caller-owned because an identical entry already
        exists (a same-prefix race within the engine — the duplicate
        block simply frees at finalize, the table keeps pointing at
        it, contents are identical by construction)."""
        bs = self.block_size
        n = self.shareable_blocks(prompt)
        entries: List[PrefixEntry] = []
        leftover: List[int] = []
        h = chain_hash
        tick = next(self._tick)
        broken = False
        for j, block in enumerate(blocks):
            i = start_block + j
            if i >= n or broken:
                leftover.append(int(block))
                continue
            nxt = _chain_hash(h, prompt[i * bs:(i + 1) * bs])
            if nxt in self._entries:
                # duplicate chain suffix: keep ours caller-owned, and
                # stop extending (a child of OUR unregistered block
                # must not attach under the existing entry's chain)
                leftover.append(int(block))
                broken = True
                continue
            e = PrefixEntry(nxt, h if h else None, block)
            self._ref(e)
            e.last_used = tick
            self._entries[nxt] = e
            parent = self._entries.get(h) if h else None
            if parent is not None:
                parent.children += 1
            entries.append(e)
            h = nxt
        return entries, leftover

    def release(self, entries: Sequence[PrefixEntry]):
        """Drop one reference per entry (request finalize).  Entries
        stay cached at ``refs == 0`` — idle and warm — until eviction
        pressure reclaims them."""
        for e in entries:
            assert e.refs > 0, "release() without matching reference"
            e.refs -= 1
            if e.refs == 0 and self.pin_referenced:
                self._alloc.unpin(1)

    # -- eviction ------------------------------------------------------------
    def _evictable(self) -> Optional[PrefixEntry]:
        best: Optional[PrefixEntry] = None
        for e in self._entries.values():
            if e.refs > 0 or e.children > 0:
                continue
            if best is None or e.last_used < best.last_used:
                best = e
        return best

    def evict_one(self) -> Optional[int]:
        """Reclaim the least-recently-used idle *leaf* entry; returns
        the freed block id (freed back to the allocator) or None."""
        e = self._evictable()
        if e is None:
            return None
        del self._entries[e.key]
        if e.parent is not None:
            p = self._entries.get(e.parent)
            if p is not None:
                p.children -= 1
        self._alloc.free([e.block])
        self.evictions += 1
        return e.block

    def ensure_free(self, n: int):
        """Make the allocator able to satisfy ``allocate(n)`` by
        evicting idle entries.  Under reservation-gated admission this
        cannot fail for an admitted request: idle cached blocks are
        the only pool occupancy beyond the reservation envelope.  An
        un-reserved caller can still exhaust a pool whose live blocks
        cover it — that raises :class:`OutOfBlocks` exactly like the
        allocator itself would."""
        while self._alloc.num_free < int(n):
            if self.evict_one() is None:
                raise OutOfBlocks(
                    f"ensure_free({n}): {self._alloc.num_free} free, "
                    f"no idle prefix entries left to evict "
                    f"(cached={self.cached_blocks}, "
                    f"live_refs={self.live_refs})")

    def clear(self):
        """Drop every idle entry (engine teardown); entries still
        referenced by live tables are kept and reported."""
        while self.evict_one() is not None:
            pass
        return len(self._entries)
