"""Paged KV cache: fixed-size blocks in one preallocated device pool.

The serving decode path (Ragged Paged Attention, PAPERS.md arxiv
2604.15464) keeps every request's K/V in fixed-size *blocks* drawn from
a single preallocated pool instead of one contiguous per-request
buffer.  Mixed-length requests then share ONE compiled decode program:
the per-request layout lives in an integer page table, which is data,
not shape — requests joining and leaving the running batch never
change a traced shape, so nothing recompiles.

Split of responsibilities:

- ``BlockAllocator`` (host): free-list bookkeeping — allocate /
  append-grow / free plus the worst-case *reservation* accounting the
  scheduler's admission control uses so a request admitted today can
  never OOM the pool mid-decode tomorrow.
- ``PagedKVCache`` (host handle, device pool): owns the pool array
  ``[L, 2, num_blocks, block_size, H, Dh]`` and the per-request page
  tables.  The pool array itself is handed to the compiled decode step
  as a DONATED argument and rides the dispatch chain device-resident;
  this class only ever swaps its handle for the step's output.
- pure pool ops (``write_prompt_pages`` / ``paged_append`` /
  ``gather_pages``): shape-stable jnp functions traced INTO the
  compiled prefill/decode programs.

Block 0 is the scratch block: it is never allocated, and every masked
write (inactive slot, done request, bucket-padding tail) is routed to
it, so the compiled step needs no branch — writes always happen, only
the target differs.  Nothing ever reads scratch: ragged attention
masks by per-request length.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp

#: block id that absorbs masked writes; never allocated, never read
SCRATCH_BLOCK = 0


class OutOfBlocks(RuntimeError):
    """The pool cannot satisfy an allocation (admission-control bug or
    an un-reserved caller)."""


class BlockAllocator:
    """Free-list allocator over the block pool (host side).

    Fragmentation-aware in two ways:

    - ``allocate(n)`` first looks for the *smallest contiguous run*
      that fits (best-fit): contiguous pages let a bucket prefill land
      as one dense slice write, and keeping the remaining free space
      in large runs preserves that for later requests.  When no single
      run fits, it falls back to scattered lowest-index-first blocks —
      paged attention is layout-indifferent, so fragmentation degrades
      nothing but the write pattern.
    - ``stats()`` reports the run structure (``largest_run``,
      ``fragmentation``) so the serving stats surface can watch decay.

    Reservations: ``reserve(n)`` / ``release(n)`` track the worst-case
    block need of every admitted request WITHOUT allocating.  Admission
    control only admits while ``reserved + pinned + need <= capacity``;
    actual ``allocate`` calls then draw lazily (prompt blocks at
    prefill, one block at a time as decode crosses block boundaries)
    and can never fail for an admitted request.

    Pins: ``pin(n)`` / ``unpin(n)`` count blocks that are occupied,
    un-evictable, and NOT covered by any reservation — prefix-cache
    blocks referenced by live requests under reservation-discounted
    admission (DESIGN-SERVING.md §Disaggregated tier).  The classic
    admission path never pins; the envelope then degenerates to the
    original ``reserved <= capacity``.

    Page migration: ``export_blocks`` / ``import_blocks`` are the
    allocator half of the disaggregated tier's page-migration API —
    an export returns a request's pages to this pool (the K/V has
    been copied out), an import draws fresh pages for K/V copied in.
    Accounting-wise they are free/allocate with intent and lifetime
    counters; the device copy itself is the engine's jitted
    gather/scatter (``migration.py``).
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is scratch)")
        self._free = sorted(range(1, num_blocks))  # block 0 = scratch
        self._allocated: set = set()
        self.capacity = num_blocks - 1
        self._reserved = 0
        self._pinned = 0
        self.exported_blocks = 0       # lifetime migration counters
        self.imported_blocks = 0

    # -- reservations (admission control) -----------------------------------
    @property
    def reserved(self) -> int:
        return self._reserved

    @property
    def pinned(self) -> int:
        return self._pinned

    def can_reserve(self, n: int) -> bool:
        return self._reserved + self._pinned + int(n) <= self.capacity

    def reserve(self, n: int) -> bool:
        if not self.can_reserve(n):
            return False
        self._reserved += int(n)
        return True

    def release(self, n: int):
        self._reserved -= int(n)
        assert self._reserved >= 0, "release() without matching reserve()"

    def pin(self, n: int = 1):
        """Count ``n`` occupied blocks into the admission envelope that
        no reservation covers (live-referenced prefix-cache blocks
        under discounted admission).  Without the pin, two requests
        whose reservations were discounted against DIFFERENT cached
        prefixes could jointly out-demand the pool mid-decode."""
        self._pinned += int(n)

    def unpin(self, n: int = 1):
        self._pinned -= int(n)
        assert self._pinned >= 0, "unpin() without matching pin()"

    # -- page migration (disaggregated serving) ------------------------------
    def export_blocks(self, blocks: Sequence[int]) -> int:
        """Give a migrating request's pages back to this pool: the K/V
        they held has been copied into another engine's pool, so an
        export IS a free — validated against double-export exactly
        like ``free`` — plus the lifetime counter ``stats()`` surfaces.
        Returns the number of blocks exported."""
        blocks = [int(b) for b in blocks]
        self.free(blocks)
        self.exported_blocks += len(blocks)
        return len(blocks)

    def import_blocks(self, n: int) -> List[int]:
        """Draw ``n`` fresh pages for K/V migrating INTO this pool.
        Same contract as ``allocate`` (the importer must hold a
        reservation); the page-table remap is the caller's: migrated
        block ids are this pool's, never the source's."""
        got = self.allocate(n)
        self.imported_blocks += len(got)
        return got

    # -- allocate / free -----------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def _runs(self) -> List[List[int]]:
        """Maximal contiguous runs of the (sorted) free list."""
        runs: List[List[int]] = []
        for b in self._free:
            if runs and runs[-1][-1] == b - 1:
                runs[-1].append(b)
            else:
                runs.append([b])
        return runs

    def allocate(self, n: int) -> List[int]:
        """n block ids — contiguous best-fit, else scattered lowest-first.

        Raises :class:`OutOfBlocks` when the pool cannot satisfy it;
        under reservation-gated admission that means a caller skipped
        ``reserve()``.
        """
        n = int(n)
        if n <= 0:
            return []
        if n > len(self._free):
            raise OutOfBlocks(
                f"allocate({n}): only {len(self._free)} free blocks "
                f"(capacity {self.capacity}, reserved {self._reserved})")
        best: Optional[List[int]] = None
        for run in self._runs():
            if len(run) >= n and (best is None or len(run) < len(best)):
                best = run
        got = best[:n] if best is not None else self._free[:n]
        got_set = set(got)
        self._free = [b for b in self._free if b not in got_set]
        self._allocated |= got_set
        return got

    def free(self, blocks: Sequence[int]):
        for b in blocks:
            b = int(b)
            if b not in self._allocated:
                raise ValueError(f"free({b}): block is not allocated")
            self._allocated.discard(b)
        merged = sorted(set(self._free) | {int(b) for b in blocks})
        self._free = merged

    def stats(self) -> Dict[str, float]:
        runs = self._runs()
        largest = max((len(r) for r in runs), default=0)
        free = len(self._free)
        return {
            "capacity": self.capacity,
            "free": free,
            "allocated": len(self._allocated),
            "reserved": self._reserved,
            "pinned": self._pinned,
            "exported_blocks": self.exported_blocks,
            "imported_blocks": self.imported_blocks,
            "free_runs": len(runs),
            "largest_run": largest,
            # 0.0 = one contiguous run (or empty), → 1.0 = maximally
            # scattered free space
            "fragmentation": (1.0 - largest / free) if free else 0.0,
        }


class PageTable:
    """Per-request block list + length (host bookkeeping)."""

    __slots__ = ("blocks", "length")

    def __init__(self):
        self.blocks: List[int] = []
        self.length = 0


class PagedKVCache:
    """The device pool + host page tables for one serving engine.

    ``pool``: ``[num_layers, 2, num_blocks, block_size, heads, head_dim]``
    (axis 1 = K/V).  The handle held here is *donated* into every
    compiled prefill-write and decode dispatch; callers must adopt the
    returned array via :meth:`swap_pool` — the old buffer is gone.
    """

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 num_heads: int, head_dim: int, dtype=jnp.float32):
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.pool = jnp.zeros(
            (num_layers, 2, num_blocks, block_size, num_heads, head_dim),
            dtype=dtype)
        self.allocator = BlockAllocator(num_blocks)

    def swap_pool(self, new_pool):
        self.pool = new_pool

    def blocks_for_tokens(self, n_tokens: int,
                          lookahead: int = 0) -> int:
        """Pages covering ``n_tokens`` committed positions plus
        ``lookahead`` uncommitted write positions past them (the
        speculative window's in-flight draft/verify appends)."""
        return -(-(int(n_tokens) + int(lookahead)) // self.block_size)


# ---------------------------------------------------------------------------
# pure pool ops (traced into the compiled prefill/decode programs)
# ---------------------------------------------------------------------------
def write_prompt_pages(pool, kv, block_ids):
    """Scatter a prefill's K/V into its pages.

    ``kv``: ``[L, 2, Lb, H, Dh]`` with ``Lb = len(block_ids) *
    block_size`` (prefill buckets are whole blocks).  ``block_ids``
    ``[nb]`` int32 — tail entries past the prompt's real blocks point
    at SCRATCH_BLOCK, absorbing the bucket padding.  Duplicate scratch
    indices make the scatter order-dependent only inside scratch,
    which is never read.
    """
    L, two, Lb, H, Dh = kv.shape
    nb = block_ids.shape[0]
    bs = Lb // nb
    kvp = kv.reshape(L, two, nb, bs, H, Dh)
    return pool.at[:, :, block_ids].set(kvp)


def write_prompt_pages_group(pool, kv, block_ids):
    """Grouped variant of :func:`write_prompt_pages`: one scatter for
    a whole same-bucket prefill group (DESIGN-SERVING.md
    §Long-context tier — batched same-bucket prefill).

    ``kv``: ``[L, 2, G, Lb, H, Dh]``; ``block_ids`` ``[G, nb]`` int32
    (dummy group rows and bucket-padding tails point at
    SCRATCH_BLOCK).  Scatter collisions exist only inside scratch,
    which is never read.
    """
    L, two, G, Lb, H, Dh = kv.shape
    nb = block_ids.shape[1]
    bs = Lb // nb
    kvp = kv.reshape(L, two, G, nb, bs, H, Dh)
    return pool.at[:, :, block_ids].set(kvp)


def paged_append(pool, layer, k_new, v_new, block_ids, offsets):
    """Write one decode token's K/V per request into its current page.

    ``k_new``/``v_new``: ``[B, H, Dh]``; ``block_ids``/``offsets``:
    ``[B]`` int32 (masked rows target SCRATCH_BLOCK).
    """
    pool = pool.at[layer, 0, block_ids, offsets].set(k_new)
    pool = pool.at[layer, 1, block_ids, offsets].set(v_new)
    return pool


def gather_pages(pool, layer, page_table):
    """Page-table gather → per-request contiguous K/V views.

    ``page_table`` ``[B, max_blocks]`` int32 → ``(k, v)`` each
    ``[B, max_blocks * block_size, H, Dh]``.  Unused table tail entries
    are SCRATCH_BLOCK; whatever they gather is masked by length in
    ragged attention.
    """
    k = pool[layer, 0][page_table]          # [B, nb, bs, H, Dh]
    v = pool[layer, 1][page_table]
    B, nb, bs, H, Dh = k.shape
    return (k.reshape(B, nb * bs, H, Dh),
            v.reshape(B, nb * bs, H, Dh))
