"""Continuous-batching scheduler: request queue + admission control.

FCFS with block-budget admission (DESIGN-SERVING.md §Scheduler):

- ``submit`` enqueues up to ``max_queue`` waiting requests; beyond
  that it REJECTS (:class:`QueueFull`) instead of buffering unbounded
  — under heavy traffic the caller's load balancer must see
  backpressure, not a silently growing latency cliff.
- A waiting request is admitted into the running batch when (a) a
  batch slot is free and (b) the allocator can *reserve* its
  worst-case block need ``ceil((len(prompt) + max_tokens) / bs)``.
  Reservation-gated admission means an admitted request can never
  fail a mid-decode block allocation: the pool math is settled at the
  door, so the hot loop has no OOM/eviction path at all (the
  trade-off — conservative vs optimistic admission — is documented in
  DESIGN-SERVING.md).
- FCFS order is strict: a large request at the head blocks smaller
  ones behind it (no starvation of big prompts).  Head-of-line
  reordering is a policy knob deliberately NOT taken — see the design
  doc for why.

Thread model: ``submit`` may be called from any thread (the server
front door); ``pop_admissible`` runs only on the engine thread.  One
lock guards the deque; nothing here touches the device.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from concurrent.futures import Future


class QueueFull(RuntimeError):
    """Admission queue is at capacity — shed load upstream."""


class RequestStats:
    """Host-clock latency milestones for one request (all
    ``time.monotonic`` seconds; device work is asynchronous, so these
    measure the *dispatch* timeline the client actually experiences)."""

    __slots__ = ("submitted", "admitted", "first_token", "finished",
                 "prompt_len", "generated")

    def __init__(self):
        self.submitted: float = 0.0
        self.admitted: Optional[float] = None
        self.first_token: Optional[float] = None
        self.finished: Optional[float] = None
        self.prompt_len: int = 0
        self.generated: int = 0

    @property
    def latency(self) -> Optional[float]:
        if self.finished is None:
            return None
        return self.finished - self.submitted

    @property
    def queue_time(self) -> Optional[float]:
        if self.admitted is None:
            return None
        return self.admitted - self.submitted

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token (prefill emits it)."""
        if self.first_token is None:
            return None
        return self.first_token - self.submitted

    def as_dict(self):
        return {"prompt_len": self.prompt_len,
                "generated": self.generated,
                "latency_s": self.latency,
                "queue_time_s": self.queue_time,
                "ttft_s": self.ttft}


class Request:
    """One generation request riding through the engine."""

    _ids = itertools.count()

    def __init__(self, prompt_ids, max_tokens: int,
                 stream_cb: Optional[Callable] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None):
        self.id = next(Request._ids)
        self.prompt = [int(t) for t in prompt_ids]
        if not self.prompt:
            raise ValueError("empty prompt")
        self.max_tokens = int(max_tokens)
        if self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        # sampling semantics (DESIGN-SERVING.md §Long-context tier):
        # temperature 0 = greedy; top_k <= 0 / top_p >= 1 disable the
        # respective filter; seed None derives a per-request default
        # (request id) so unseeded sampled requests differ.  All four
        # ride the compiled decode step as [B] data vectors.
        self.temperature = float(temperature)
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        self.seed = int(seed) if seed is not None else self.id
        self.stream_cb = stream_cb
        self.future: Future = Future()
        self.stats = RequestStats()
        self.stats.prompt_len = len(self.prompt)
        self.stats.submitted = time.monotonic()
        # engine-side state
        self.slot: Optional[int] = None
        self.blocks: List[int] = []     # exclusively-owned pool blocks
        self.prefix_entries: list = []  # PrefixCache refs (shared)
        # reserved_blocks is admission ACCOUNTING (released at finish,
        # shrunk by discounted-mode cache inserts); block_budget is the
        # page-table growth CAP (always the worst case) — the two
        # coincide only under undiscounted admission
        self.reserved_blocks = 0
        self.block_budget = 0
        self.lazy_tokens: list = []     # per-step lazy device views
        self.capped = False             # page growth stopped (done-lag)

    @property
    def n_prefix_blocks(self) -> int:
        """Table entries borrowed from the prefix cache (shared,
        cache-owned; the request holds one reference each)."""
        return len(self.prefix_entries)

    def worst_case_blocks(self, block_size: int,
                          lookahead: int = 0) -> int:
        # prompt positions + one cache write per decode dispatch
        # (the last generated token is emitted, never written);
        # `lookahead` extends the envelope for engines that write past
        # the committed length each dispatch (speculative decoding
        # writes up to k look-ahead positions before knowing how many
        # commit — DESIGN-SERVING.md §Speculative tier)
        need = len(self.prompt) + self.max_tokens - 1 + int(lookahead)
        return -(-need // block_size)

    def push_token(self, lazy_tok, t_now: float):
        if not self.lazy_tokens:
            self.stats.first_token = t_now
        self.lazy_tokens.append(lazy_tok)
        self.stats.generated = len(self.lazy_tokens)
        if self.stream_cb is not None:
            # lazy delivery: reading/formatting the token is the
            # consumer's sync, not the engine's
            self.stream_cb(self.id, len(self.lazy_tokens) - 1, lazy_tok)


class Scheduler:
    """FCFS waiting queue with block-budget admission control."""

    def __init__(self, allocator, block_size: int, max_queue: int = 64,
                 max_context: Optional[int] = None,
                 door_need_fn: Optional[Callable] = None,
                 lookahead: int = 0):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.max_queue = int(max_queue)
        self.max_context = max_context
        # per-dispatch write look-ahead folded into every worst-case
        # envelope (admission reservation AND growth budget) so a
        # speculative engine's k uncommitted writes can never outrun a
        # request's allocation, whatever the rejection churn
        self.lookahead = int(lookahead)
        # the submit-door capacity sanity check: how many blocks this
        # ENGINE will ever hold for the request.  Default worst case;
        # a prefill-role engine overrides with prompt-blocks-only —
        # the decode blocks belong to the importing replica's pool,
        # so gating its door on max_tokens would refuse long streams
        # a disaggregated deployment serves fine.
        self._door_need_fn = door_need_fn
        self._waiting: deque = deque()
        self._lock = threading.Lock()

    # -- front door ----------------------------------------------------------
    def submit(self, req: Request) -> Request:
        need = (self._door_need_fn(req)
                if self._door_need_fn is not None
                else req.worst_case_blocks(self.block_size,
                                           self.lookahead))
        if need > self.allocator.capacity:
            raise ValueError(
                f"request needs {need} blocks worst-case but the pool "
                f"only has {self.allocator.capacity}; lower max_tokens "
                "or grow num_blocks")
        if self.max_context is not None and \
                len(req.prompt) + req.max_tokens - 1 > self.max_context:
            raise ValueError(
                f"prompt+max_tokens ({len(req.prompt)}+{req.max_tokens})"
                f" exceeds max context {self.max_context}")
        with self._lock:
            if len(self._waiting) >= self.max_queue:
                raise QueueFull(
                    f"admission queue at capacity ({self.max_queue}); "
                    "shed load upstream")
            self._waiting.append(req)
        return req

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._waiting)

    # -- engine side ---------------------------------------------------------
    def pop_admissible(self, free_slots: int,
                       need_fn: Optional[Callable] = None,
                       cancel_fn: Optional[Callable] = None
                       ) -> List[Request]:
        """Admit FCFS-head requests while slots and block reservations
        allow; reservations are taken here, released at finish.

        ``need_fn(req) -> int`` overrides the worst-case reservation —
        the engine supplies it for phase-specialized replicas (a
        prefill-role engine reserves prompt blocks only; the decode
        blocks are the importing replica's to reserve) and for
        reservation-discounted admission (need minus live prefix-cache
        hits).  A need_fn may acquire side state (prefix references);
        ``cancel_fn(req)`` releases it when the reservation is refused
        and the request stays queued.  ``block_budget`` is always the
        undiscounted worst case: the growth cap is about table extent,
        not about who accounts for the blocks."""
        admitted: List[Request] = []
        now = time.monotonic()
        with self._lock:
            while free_slots > 0 and self._waiting:
                req = self._waiting[0]
                need = (need_fn(req) if need_fn is not None
                        else req.worst_case_blocks(self.block_size,
                                                   self.lookahead))
                if not self.allocator.reserve(need):
                    if cancel_fn is not None:
                        cancel_fn(req)
                    break           # strict FCFS: no head-of-line skip
                self._waiting.popleft()
                req.reserved_blocks = need
                req.block_budget = req.worst_case_blocks(
                    self.block_size, self.lookahead)
                req.stats.admitted = now
                admitted.append(req)
                free_slots -= 1
        return admitted

    def release_partial(self, req: Request, n: int):
        """Shrink a live request's reservation by ``n`` blocks
        (discounted-admission mode: blocks whose ownership moved to
        the prefix cache are accounted by the allocator pin from that
        moment, so keeping them reserved would double-count)."""
        n = min(int(n), req.reserved_blocks)
        if n > 0:
            self.allocator.release(n)
            req.reserved_blocks -= n

    def drain_waiting(self) -> List[Request]:
        """Remove and return EVERY waiting request unconditionally
        (server teardown/failure path — reservations don't gate it)."""
        with self._lock:
            out = list(self._waiting)
            self._waiting.clear()
        return out

    def finish(self, req: Request):
        """Release the request's block reservation (engine frees the
        actual blocks through the allocator separately)."""
        if req.reserved_blocks:
            self.allocator.release(req.reserved_blocks)
            req.reserved_blocks = 0
