"""Speculative multi-token decoding INSIDE the one decode program
(DESIGN-SERVING.md §Speculative tier).

The decode loop's biggest remaining cost on this repo's CPU host-loop
proxy is the same one fold-K attacked in training (DESIGN-PERF.md):
one host dispatch per emitted token.  This module folds up to ``k+1``
token emissions into ONE compiled dispatch while preserving the
serving stack's exactness contract bit for bit:

1. **Draft.**  A small draft model (same pool geometry as the target;
   self-draft = the target's own weights) proposes ``k`` tokens by
   running ``k`` sequential single-token decode forwards against the
   SHARED paged pool.  Its interim K/V writes land in the look-ahead
   positions and are overwritten by the verify pass below — the draft
   never owns cache state.
2. **Verify.**  The target model scores all ``k+1`` positions (the
   incoming token plus the k proposals) in ONE batched forward:
   :func:`~.decode_model.spec_score_forward` flattens the window into
   the batch axis, so each window row appends its own K/V page write
   and attends causally over the pool through the existing ragged
   paged-attention seam — no new attention math, no new scatter.
3. **Accept/reject.**  Sampling is deterministic Gumbel-max on
   ``fold_in(seed, position)`` keys (``sampling.py``), so the target's
   "own" token at every position is a pure function of (prefix
   logits, seed, position).  A proposal is accepted iff it EQUALS the
   target's choice at that position; the first mismatch emits the
   target's verified token instead and the window ends.  The emitted
   sequence is therefore token-IDENTICAL to what sequential
   non-speculative decoding would produce — greedy and seeded
   sampling alike — whatever the draft proposed.  Rejection sampling
   composes with the PR-14 machinery trivially because the
   Gumbel-max draw IS the target distribution sample; determinism and
   join/leave invariance carry over unchanged.

Accepted prefixes need no commit step: the verify forward already
wrote the target K/V for every window position through the same
page-write scatter the plain decode step uses, and positions beyond
the accepted prefix are masked by length in every later read (the
page-padding argument, DESIGN-SERVING.md §Exactness).

Rejected-position emissions are :data:`SPEC_SENTINEL` so the host can
push a fixed ``k+1`` lazy views per dispatch without syncing on the
accept count; real lengths ride the loop device-resident and
reconcile at the engine's one whitelisted poll.
"""

from __future__ import annotations

import jax.numpy as jnp

from .decode_model import decode_forward, spec_score_forward
from .sampling import sample_tokens, sample_tokens_grid

#: emitted-token placeholder for positions past the accepted prefix —
#: never a valid vocab id, stripped host-side at finalize/stream
SPEC_SENTINEL = -1


def spec_decode_step(params, draft_params, cfg, k, pool, page_table,
                     lengths, tokens, active, temps, topks, topps,
                     seeds, attention="gather"):
    """One speculative window for the whole batch, fully in-program.

    ``lengths``/``tokens``/``active`` as in
    :func:`~.decode_model.decode_forward`; ``k`` is a static trace
    constant (the draft loop unrolls).  Returns ``(pool, emit
    [B, k+1], last [B], n_emit [B])`` where ``emit`` holds the
    accepted prefix plus the verified correction/bonus token
    (:data:`SPEC_SENTINEL` beyond it), ``last`` is the final emitted
    token per row (the next dispatch's input), and ``n_emit`` is the
    number of real tokens emitted (0 for inactive rows).
    """
    B = tokens.shape[0]
    S = k + 1
    # -- 1) draft proposal loop: k sequential forwards on the shared
    # pool.  Proposals use the SAME (seed, position) keys as the
    # target, so a self-draft agrees with the verify pass exactly and
    # the accept rate is 1 by construction.
    props = []
    d_tok, d_len = tokens, lengths
    for _ in range(k):
        pool, d_logits = decode_forward(draft_params, cfg, pool,
                                        page_table, d_len, d_tok,
                                        active, attention=attention)
        d_tok = sample_tokens(d_logits, temps, topks, topps, seeds,
                              d_len + 1)
        props.append(d_tok)
        d_len = d_len + 1
    props = jnp.stack(props, axis=1)                       # [B, k]
    window = jnp.concatenate([tokens[:, None], props], axis=1)
    # -- 2) verify: target scores all k+1 positions in ONE forward,
    # overwriting the draft's interim K/V with the target's own
    pool, logits = spec_score_forward(params, cfg, pool, page_table,
                                      lengths, window, active,
                                      attention=attention)
    # -- 3) the target's deterministic choice at every window
    # position: fold_in(seed, position) keys, position = the sampled
    # token's sequence index, exactly as the plain decode step
    offs = jnp.arange(S, dtype=jnp.int32)
    positions = lengths[:, None] + 1 + offs[None]          # [B, S]
    choices = sample_tokens_grid(logits, temps, topks, topps, seeds,
                                 positions)                # [B, S]
    # -- 4) accept the longest proposal prefix that matches the
    # target's own choices; slot a = first mismatch emits the
    # verified token (a == k emits the bonus token)
    match = (props == choices[:, :k]).astype(jnp.int32)    # [B, k]
    acc = jnp.cumprod(match, axis=1).sum(axis=1)           # [B]
    valid = (offs[None] <= acc[:, None]) & active[:, None]
    emit = jnp.where(valid, choices, jnp.int32(SPEC_SENTINEL))
    last = jnp.take_along_axis(choices, acc[:, None], axis=1)[:, 0]
    last = jnp.where(active, last, tokens)
    n_emit = jnp.where(active, acc + 1, 0).astype(jnp.int32)
    return pool, emit, last, n_emit
