"""Ragged batched attention over paged KV (PAPERS.md arxiv 2604.15464).

One compiled program serves a batch of requests whose context lengths
all differ: K/V come in via the page-table gather (``kv_cache.
gather_pages``) padded to the table's maximum extent, and a per-request
``lengths`` vector masks the tail.  The mask arithmetic is built for
*exactness* against a per-request dense-cache reference:

- masked logits are set to a large finite negative (never ``-inf``):
  after max-subtraction their ``exp`` underflows to exactly ``0.0``,
  and an explicit ``where`` pins them to ``0.0`` regardless of
  magnitude, so padding contributes exact zeros to the softmax sums;
- the denominator is ``maximum(sum, tiny)``: for any row with at least
  one valid position the sum is ``>= exp(0) = 1``, so the guard is
  bit-inert there, while an all-masked row (empty batch slot) yields
  ``0`` output instead of ``0/0 = NaN`` — NaN in a dead slot would
  still poison XLA fast-math assumptions and trip ``nan`` debug modes;
- statistics run in f32 like the training stack's attention
  (``ops/nn_ops.py _sdpa``), output returns in the input dtype.

Remaining difference vs the sequential reference is reduction order
over the padded axis (XLA picks the tree by extent) — ~1 ulp on
logits; greedy token choices match exactly (tests pin both, see
DESIGN-SERVING.md §Exactness).
"""

from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp

from ...framework import env_knobs

#: large-finite mask value (``-inf`` breeds NaN under 0*inf folding)
MASK_VALUE = -1e30
#: denominator guard — bit-inert for any row with >= 1 valid position
DENOM_TINY = 1e-30

#: env knob for the decode-attention implementation behind
#: :func:`paged_decode_attention` (DESIGN-SERVING.md §Long-context
#: tier): "gather" = the reference gather+mask composition, "pallas" =
#: the fused paged kernel (interpret mode off-TPU), "auto" = pallas on
#: a TPU backend, gather elsewhere.
PAGED_ATTENTION_ENV = "PADDLE_TPU_PAGED_ATTENTION"


def resolve_paged_attention_mode(mode=None) -> str:
    """Resolve the decode-attention implementation once, at engine
    build time (the decision is baked into the compiled decode step,
    never re-read per dispatch).  Explicit ``mode`` wins, then the
    ``PADDLE_TPU_PAGED_ATTENTION`` env knob, then capability: the
    fused kernel compiles through Mosaic on TPU and through the
    Pallas interpreter elsewhere — interpretation is correct but
    host-paced, so off-TPU the gather composition stays the default
    and the kernel is an opt-in (tests/bench pin it)."""
    m = (mode if mode is not None
         else env_knobs.get_raw(PAGED_ATTENTION_ENV,
                                "auto")).strip().lower()
    if m in ("", "auto"):
        return "pallas" if jax.default_backend() == "tpu" else "gather"
    if m in ("0", "ref", "reference", "gather"):
        return "gather"
    if m in ("1", "pallas", "kernel"):
        return "pallas"
    raise ValueError(
        f"unknown paged-attention mode {mode!r} (expected auto | "
        "gather | pallas)")


def paged_decode_attention(pool, layer, page_table, lengths, q,
                           mode: str = "gather"):
    """THE decode-attention seam: per-request single-token queries
    against the paged KV pool, page layout as data.

    ``pool`` ``[L, 2, NB, BS, H, Dh]``; ``page_table`` ``[B, MAXNB]``
    int32; ``lengths`` ``[B]`` int32 (positions ``t < lengths[b]``
    attend); ``q`` ``[B, H, Dh]``.  ``mode`` is a *resolved* mode
    string (see :func:`resolve_paged_attention_mode`) — a static
    trace-time choice:

    - ``"gather"``: the CPU/parity reference — materialize the padded
      ``[B, MAXNB*BS, H, Dh]`` gather, mask by length;
    - ``"pallas"``: the fused kernel walks pages block-by-block with
      an online softmax, working set one block per request
      (``paged_attention_kernel.py``).
    """
    if mode == "pallas":
        from .paged_attention_kernel import paged_ragged_attention
        return paged_ragged_attention(
            pool[layer, 0], pool[layer, 1], page_table, lengths, q,
            interpret=jax.default_backend() != "tpu")
    from .kv_cache import gather_pages
    kp, vp = gather_pages(pool, layer, page_table)
    return ragged_decode_attention(q, kp, vp, lengths)


def ragged_decode_attention(q, k, v, lengths, scale=None):
    """Single-token queries against per-request ragged contexts.

    ``q`` ``[B, H, Dh]``; ``k``/``v`` ``[B, T, H, Dh]`` (page-table
    gather, padded to the common ``T``); ``lengths`` ``[B]`` int32 —
    request ``b`` attends positions ``t < lengths[b]``.  Returns
    ``[B, H, Dh]`` in ``q``'s dtype.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    orig = q.dtype
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    logits = jnp.einsum("bhd,bthd->bht", qf, kf) * scale
    T = k.shape[1]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < \
        lengths.astype(jnp.int32)[:, None]               # [B, T]
    logits = jnp.where(valid[:, None, :], logits, MASK_VALUE)
    m = jnp.max(logits, axis=-1, keepdims=True)
    w = jnp.exp(logits - m)
    w = jnp.where(valid[:, None, :], w, 0.0)
    denom = jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), DENOM_TINY)
    probs = w / denom
    out = jnp.einsum("bht,bthd->bhd", probs, vf)
    return out.astype(orig)


def chunked_prefill_attention(q, k_ctx, v_ctx, ctx_len, k_chunk,
                              v_chunk, scale=None):
    """Attention for one prefill *chunk* against cached context plus
    itself (DESIGN-SERVING.md §Long-context tier: chunk admission).

    ``q``/``k_chunk``/``v_chunk`` ``[B, C, H, Dh]`` — the chunk's
    projections; ``k_ctx``/``v_ctx`` ``[B, T, H, Dh]`` — the page
    gather of everything already in cache (prefix-cache hits and
    earlier chunks), padded to ``T``; ``ctx_len`` int32 scalar — the
    real context extent (positions ``t < ctx_len`` attend).  Chunk row
    ``i`` (global position ``ctx_len + i``) attends the full valid
    context plus chunk positions ``j <= i`` — exactly the rows a
    whole-prompt causal prefill computes for those positions, so chunk
    boundaries change only reduction order (same masked-softmax
    arithmetic, exact zeros, f32 statistics).  Returns
    ``[B, C, H, Dh]`` in ``q``'s dtype.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    orig = q.dtype
    qf = q.astype(jnp.float32)
    C = q.shape[1]
    T = k_ctx.shape[1]
    lg_ctx = jnp.einsum("bqhd,bkhd->bhqk", qf,
                        k_ctx.astype(jnp.float32)) * scale
    lg_chk = jnp.einsum("bqhd,bkhd->bhqk", qf,
                        k_chunk.astype(jnp.float32)) * scale
    ctx_valid = jnp.arange(T, dtype=jnp.int32)[None, None, None, :] < \
        jnp.asarray(ctx_len, jnp.int32)                  # [1,1,1,T]
    causal = jnp.tril(jnp.ones((C, C), dtype=bool))[None, None]
    lg_ctx = jnp.where(ctx_valid, lg_ctx, MASK_VALUE)
    lg_chk = jnp.where(causal, lg_chk, MASK_VALUE)
    logits = jnp.concatenate([lg_ctx, lg_chk], axis=-1)  # [B,H,C,T+C]
    valid = jnp.concatenate(
        [jnp.broadcast_to(ctx_valid, lg_ctx.shape),
         jnp.broadcast_to(causal, lg_chk.shape)], axis=-1)
    m = jnp.max(logits, axis=-1, keepdims=True)
    w = jnp.exp(logits - m)
    w = jnp.where(valid, w, 0.0)
    denom = jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), DENOM_TINY)
    probs = w / denom
    vall = jnp.concatenate([v_ctx.astype(jnp.float32),
                            v_chunk.astype(jnp.float32)], axis=1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vall)
    return out.astype(orig)


def causal_prefill_attention(q, k, v, scale=None):
    """Dense causal attention for the prefill pass.

    ``q``/``k``/``v`` ``[B, S, H, Dh]`` → ``[B, S, H, Dh]``.  Same
    masked-softmax arithmetic as :func:`ragged_decode_attention` (exact
    zeros for masked positions, f32 statistics) so a bucket-padded
    prefill computes bit-identical rows for the real prompt positions:
    a padded tail row only ever *attends*, it is never attended to by
    a real row (causal), and its K/V are masked downstream by length.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    orig = q.dtype
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    S = q.shape[1]
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))
    logits = jnp.where(causal[None, None], logits, MASK_VALUE)
    m = jnp.max(logits, axis=-1, keepdims=True)
    w = jnp.exp(logits - m)
    w = jnp.where(causal[None, None], w, 0.0)
    denom = jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), DENOM_TINY)
    probs = w / denom
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    return out.astype(orig)
