"""Ragged batched attention over paged KV (PAPERS.md arxiv 2604.15464).

One compiled program serves a batch of requests whose context lengths
all differ: K/V come in via the page-table gather (``kv_cache.
gather_pages``) padded to the table's maximum extent, and a per-request
``lengths`` vector masks the tail.  The mask arithmetic is built for
*exactness* against a per-request dense-cache reference:

- masked logits are set to a large finite negative (never ``-inf``):
  after max-subtraction their ``exp`` underflows to exactly ``0.0``,
  and an explicit ``where`` pins them to ``0.0`` regardless of
  magnitude, so padding contributes exact zeros to the softmax sums;
- the denominator is ``maximum(sum, tiny)``: for any row with at least
  one valid position the sum is ``>= exp(0) = 1``, so the guard is
  bit-inert there, while an all-masked row (empty batch slot) yields
  ``0`` output instead of ``0/0 = NaN`` — NaN in a dead slot would
  still poison XLA fast-math assumptions and trip ``nan`` debug modes;
- statistics run in f32 like the training stack's attention
  (``ops/nn_ops.py _sdpa``), output returns in the input dtype.

Remaining difference vs the sequential reference is reduction order
over the padded axis (XLA picks the tree by extent) — ~1 ulp on
logits; greedy token choices match exactly (tests pin both, see
DESIGN-SERVING.md §Exactness).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

#: large-finite mask value (``-inf`` breeds NaN under 0*inf folding)
MASK_VALUE = -1e30
#: denominator guard — bit-inert for any row with >= 1 valid position
DENOM_TINY = 1e-30


def ragged_decode_attention(q, k, v, lengths, scale=None):
    """Single-token queries against per-request ragged contexts.

    ``q`` ``[B, H, Dh]``; ``k``/``v`` ``[B, T, H, Dh]`` (page-table
    gather, padded to the common ``T``); ``lengths`` ``[B]`` int32 —
    request ``b`` attends positions ``t < lengths[b]``.  Returns
    ``[B, H, Dh]`` in ``q``'s dtype.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    orig = q.dtype
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    logits = jnp.einsum("bhd,bthd->bht", qf, kf) * scale
    T = k.shape[1]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < \
        lengths.astype(jnp.int32)[:, None]               # [B, T]
    logits = jnp.where(valid[:, None, :], logits, MASK_VALUE)
    m = jnp.max(logits, axis=-1, keepdims=True)
    w = jnp.exp(logits - m)
    w = jnp.where(valid[:, None, :], w, 0.0)
    denom = jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), DENOM_TINY)
    probs = w / denom
    out = jnp.einsum("bht,bthd->bhd", probs, vf)
    return out.astype(orig)


def causal_prefill_attention(q, k, v, scale=None):
    """Dense causal attention for the prefill pass.

    ``q``/``k``/``v`` ``[B, S, H, Dh]`` → ``[B, S, H, Dh]``.  Same
    masked-softmax arithmetic as :func:`ragged_decode_attention` (exact
    zeros for masked positions, f32 statistics) so a bucket-padded
    prefill computes bit-identical rows for the real prompt positions:
    a padded tail row only ever *attends*, it is never attended to by
    a real row (causal), and its K/V are masked downstream by length.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    orig = q.dtype
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    S = q.shape[1]
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))
    logits = jnp.where(causal[None, None], logits, MASK_VALUE)
    m = jnp.max(logits, axis=-1, keepdims=True)
    w = jnp.exp(logits - m)
    w = jnp.where(causal[None, None], w, 0.0)
    denom = jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), DENOM_TINY)
    probs = w / denom
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    return out.astype(orig)
