"""Continuous-batching decode engine: one persistent compiled dispatch.

The serving hot loop is the training hot loop's design transplanted to
decode (DESIGN-PERF.md → DESIGN-SERVING.md): device-resident state,
donated through a cached compiled step, with host work strictly
bookkeeping-shaped and *zero* device→host syncs outside two
whitelisted points (``scripts/check_host_sync.py`` guards this module
like it guards ``Model.fit``).

Shape-stability is the whole game (arxiv 2604.15464): the decode
program is compiled ONCE for the engine's geometry —

    (params, pool [L,2,NB,BS,H,Dh], table [B,MAXNB], lengths [B],
     tokens [B], done [B]) -> (pool, tokens, done)

Requests joining and leaving the running batch mutate page-table
*data* between dispatches, never a traced shape, so membership churn
costs no recompiles (test-pinned).  With a draft artifact
(``draft=``/``PADDLE_TPU_SPEC_K``) the ONE decode program becomes its
speculative variant — the same contract, but each dispatch commits up
to ``k+1`` tokens per slot and lengths/token-counts ride the loop
device-resident (DESIGN-SERVING.md §Speculative tier).  The KV pool is donated and rides
the dispatch chain; emitted tokens feed back as the next dispatch's
input entirely on device; per-token streaming hands consumers
``LazyScalar`` views of a shared per-dispatch ``LazyStack`` — one D2H
transfer per dispatch, only if somebody actually reads.

Prefill runs per request at bucketed prompt lengths
(``io/bucketing.shape_bucket``) through one jit whose trace cache
holds one entry per bucket — the bounded compile set the bucketing
module exists for.

EOS is detected ON DEVICE (``done`` rides the loop); the host learns
of it at ``done_poll_interval`` dispatch boundaries via the single
sanctioned ``_poll_done`` sync.  Between EOS and poll a finished
request wastes masked lanes — the classic poll-cadence/occupancy
trade-off, see DESIGN-SERVING.md §EOS.  The interval is AUTO-TUNED by
default from observed dispatch economics, exactly like the training
engine's fold factor (``framework.dispatch.AutoFoldTuner``): the
first few polls measure the PURE poll cost (an empty-chain poll —
queue-drain time is device compute, not poll overhead) and the
amortized per-dispatch wall time, then the interval is frozen at the
smallest value whose amortized poll overhead is at most
``PADDLE_TPU_SERVING_POLL_TARGET`` (default 5%) of the dispatch
time, bounded by ``PADDLE_TPU_SERVING_POLL_MAX`` (default 64).  An
explicit ``done_poll_interval=`` stays fixed.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import env_knobs
from ...framework.lazy import LazyScalar, LazyStack
from ...io.bucketing import shape_bucket
from ...observability import metrics as _obs_metrics
from ...observability import trace as _obs_trace
from .decode_model import (ServingModelConfig, chunk_prefill_forward,
                           decode_forward, extract_decode_params,
                           prefill_group_forward)
from .kv_cache import SCRATCH_BLOCK, PagedKVCache
from .migration import (MigrationError, PageMigration,
                        gather_request_pages, scatter_request_pages)
from .prefix_cache import PrefixCache
from .ragged_attention import resolve_paged_attention_mode
from .sampling import sample_tokens
from .scheduler import QueueFull, Request, Scheduler
from .spec_decode import SPEC_SENTINEL, spec_decode_step

#: phase roles an engine can run as (DESIGN-SERVING.md §Disaggregated
#: tier): "both" is the classic single-engine pipeline; "prefill"
#: runs admission + (chunked) prefill only and ships every finished
#: prompt out as a PageMigration; "decode" admits ONLY migrations —
#: no prefill dispatch ever enters its program
ENGINE_ROLES = ("both", "prefill", "decode")

# synthetic Chrome-trace track ids for request lifecycle spans: one
# lane per (engine, batch slot), so concurrent requests render as
# parallel tracks instead of interleaving on the pump thread's row
_REQ_LANE_BASE = 1 << 40
_engine_ids = itertools.count()


class GenerationResult:
    """Resolved value of a request future."""

    __slots__ = ("request_id", "tokens", "stats")

    def __init__(self, request_id, tokens, stats):
        self.request_id = request_id
        self.tokens = tokens            # List[int], eos-truncated
        self.stats = stats              # RequestStats

    def __repr__(self):
        return (f"GenerationResult(id={self.request_id}, "
                f"tokens={self.tokens})")


def _pow2_buckets(max_n: int) -> List[int]:
    """1, 2, 4, … capped-at-``max_n`` buckets (group sizes, context
    block counts) — logarithmic trace sets for dimensions whose real
    extent varies per dispatch."""
    out, b = [], 1
    while b < max_n:
        out.append(b)
        b *= 2
    out.append(max_n)
    return sorted(set(out))


class _PrefillJob:
    """Host bookkeeping for one chunk-prefilling request: how much of
    the prompt is in cache (prefix hits + completed chunks), the chain
    hash where prefix-cache insertion resumes, and the pool blocks
    this request computed itself (candidate cache entries)."""

    __slots__ = ("req", "slot", "chain", "done_tokens", "insert_from",
                 "computed_blocks")

    def __init__(self, req, slot, chain, done_tokens, insert_from):
        self.req = req
        self.slot = slot
        self.chain = chain
        self.done_tokens = int(done_tokens)
        self.insert_from = int(insert_from)
        self.computed_blocks: List[int] = []


def _default_buckets(block_size: int, max_context: int) -> List[int]:
    """Power-of-two block multiples up to the context limit — few
    compiles, <= 2x padding waste per prompt.  The top bucket floors
    to a block multiple: a model whose max_position is not one (e.g.
    1000 with 16-token blocks) caps prompts at the floored length
    instead of failing the engine's bucket-alignment check."""
    top = (max_context // block_size) * block_size
    buckets, b = [], block_size
    while b < top:
        buckets.append(b)
        b *= 2
    if not buckets or buckets[-1] != top:
        buckets.append(top)
    return buckets


class DecodeEngine:
    """Continuous-batching decode over a paged KV pool.

    Drive it directly (``submit`` + ``step`` / ``run_until_idle``) or
    through :class:`~paddle_tpu.inference.serving.api.LLMServer`'s
    pump thread.  All methods except ``submit`` must be called from
    ONE thread (the pump); ``submit`` is safe from anywhere.
    """

    def __init__(self, network=None, *, gpt_config=None, params=None,
                 max_batch: int = 4, block_size: int = 16,
                 num_blocks: int = 128,
                 max_blocks_per_seq: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 done_poll_interval: Optional[int] = None,
                 max_queue: int = 64,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 attention: Optional[str] = None,
                 role: str = "both",
                 prefix_reserve_discount: bool = False,
                 device=None,
                 draft=None, draft_params=None,
                 spec_k: Optional[int] = None):
        if role not in ENGINE_ROLES:
            raise ValueError(
                f"role {role!r} is not one of {ENGINE_ROLES}")
        self.role = role
        # placement pin (disaggregated tier): every allocation and
        # dispatch this engine makes lands on `device`, so two
        # phase-pinned replicas in one process stop sharing a device
        # execution queue — the in-process analogue of phases owning
        # separate chips.  None = process default device, as before.
        self._device = device
        if network is not None:
            params = extract_decode_params(network)
            gpt_config = network.config
        if params is None or gpt_config is None:
            raise ValueError("need network= or (params=, gpt_config=)")
        self._cfg = (gpt_config
                     if isinstance(gpt_config, ServingModelConfig)
                     else ServingModelConfig.from_gpt_config(gpt_config))
        # replicas can share one network object: each engine stages
        # its own committed copy of the params on its pinned device
        self._params = (params if device is None
                        else jax.device_put(params, device))
        cfg = self._cfg
        # -- speculative tier (DESIGN-SERVING.md §Speculative tier):
        # a draft artifact turns the decode program into a k+1-token
        # speculative window.  The draft is a second prepare_serving
        # style artifact — a network to extract or an already-extracted
        # params pytree — sharing the target's pool geometry (same
        # L/H/Dh/vocab: its K/V land in the SAME pool and are
        # overwritten by the verify pass).  Heterogeneous draft
        # geometries are the multi-tenant weight-pool follow-up
        # (ROADMAP).
        if draft is not None and draft_params is None:
            draft_params = extract_decode_params(draft)
            dcfg = ServingModelConfig.from_gpt_config(draft.config)
            if dcfg != cfg:
                raise ValueError(
                    f"draft model geometry {dcfg} != target {cfg}: "
                    "speculative decoding shares the target's paged "
                    "pool, so the draft must match its serving "
                    "geometry (heterogeneous drafts need the "
                    "multi-tenant weight pool — ROADMAP)")
        if draft_params is not None:
            if self.role == "prefill":
                # a knob that cannot act must refuse: a prefill-role
                # engine's decode program never runs
                raise ValueError(
                    "draft= on a prefill-role engine: its program "
                    "never decodes, so speculation cannot act — "
                    "attach the draft to the decode replica")
            t_shapes = jax.tree_util.tree_map(lambda a: a.shape,
                                              self._params)
            d_shapes = jax.tree_util.tree_map(lambda a: a.shape,
                                              draft_params)
            if t_shapes != d_shapes:
                raise ValueError(
                    "draft_params shapes do not match the target's "
                    "serving params: speculative decoding requires "
                    "the same pool/model geometry")
            if spec_k is None:
                spec_k = env_knobs.get_int("PADDLE_TPU_SPEC_K", 4)
            if int(spec_k) < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            self.spec_k = int(spec_k)
            self._draft_params = (draft_params if device is None
                                  else jax.device_put(draft_params,
                                                      device))
        else:
            if spec_k is not None:
                raise ValueError(
                    "spec_k= without draft=/draft_params=: "
                    "speculation needs a proposal model")
            self.spec_k = 0
            self._draft_params = None
        self._spec_accept: Optional[float] = None
        # active-lane dispatch count (host view): the tokens/dispatch
        # and accept-rate aggregates normalize per LANE, not per batch
        # dispatch, so a full batch and a lone request read the same.
        # The accept GAUGE is cumulative (total committed over total
        # lane-dispatches) — a per-window value whipsaws on the tiny
        # drain windows where one lane survives; the HISTOGRAM keeps
        # the per-window distribution
        self._spec_lanes = 0
        self._spec_last_poll_lanes = 0
        self._spec_emitted = 0
        self.max_batch = int(max_batch)
        self.block_size = int(block_size)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        # None = auto-tune the poll cadence from measured dispatch
        # economics — the SAME calibrate/median/clamp policy as the
        # training engine's fold factor (AutoFoldTuner): start at 8,
        # calibrate over the first few polls, freeze
        from ...framework.dispatch import AutoFoldTuner
        self._poll_auto = done_poll_interval is None
        self.done_poll_interval = (8 if self._poll_auto
                                   else max(1, int(done_poll_interval)))
        self._poll_tuner = (AutoFoldTuner(
            target=env_knobs.get_float(
                "PADDLE_TPU_SERVING_POLL_TARGET", 0.05),
            max_fold=env_knobs.get_int(
                "PADDLE_TPU_SERVING_POLL_MAX", 64),
            calib_groups=env_knobs.get_int(
                "PADDLE_TPU_SERVING_POLL_CALIB", 3))
            if self._poll_auto else None)
        self._poll_decision: Optional[Dict] = None
        self._last_poll_end: Optional[float] = None
        self._last_poll_dispatches = 0
        if max_blocks_per_seq is None:
            max_blocks_per_seq = -(-cfg.max_position // block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.max_context = min(cfg.max_position,
                               self.max_blocks_per_seq * block_size)
        dtype = params["wte"].dtype
        with self._on_device():
            self._kv = PagedKVCache(cfg.num_layers, num_blocks,
                                    block_size, cfg.num_heads,
                                    cfg.head_dim, dtype=dtype)
        # prefill-role door: this engine only ever holds PROMPT
        # blocks (decode growth happens in the importing replica's
        # pool after handoff), so its capacity sanity check must not
        # refuse a long-max_tokens stream the deployment serves fine
        door_need = ((lambda req: -(-len(req.prompt) // block_size))
                     if self.role == "prefill" else None)
        self.scheduler = Scheduler(self._kv.allocator, block_size,
                                   max_queue=max_queue,
                                   max_context=self.max_context,
                                   door_need_fn=door_need,
                                   lookahead=self.spec_k)
        if prefill_buckets is None:
            prefill_buckets = _default_buckets(block_size,
                                               self.max_context)
        for b in prefill_buckets:
            if b % block_size:
                raise ValueError(
                    f"prefill bucket {b} is not a multiple of "
                    f"block_size {block_size}")
        self._buckets = sorted(int(b) for b in prefill_buckets)
        # -- long-context tier knobs (DESIGN-SERVING.md §Long-context
        # tier): chunked prefill, shared-prefix KV reuse, and the
        # decode-attention implementation behind the kernel seam --
        if prefill_chunk is None:
            env_chunk = env_knobs.get_raw("PADDLE_TPU_PREFILL_CHUNK",
                                          "")
            prefill_chunk = int(env_chunk) if env_chunk.strip() else None
        if prefill_chunk is not None and prefill_chunk <= 0:
            prefill_chunk = None
        if prefill_chunk is not None:
            if prefill_chunk % block_size:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} is not a multiple "
                    f"of block_size {block_size}")
            prefill_chunk = min(int(prefill_chunk), self._buckets[-1])
        self.prefill_chunk = prefill_chunk
        # chunk buckets: the final (or only) chunk of a prompt can be
        # any residue length, bucketed like legacy prefill; the
        # prefix-hit suffix path uses these even with chunking off
        self._chunk_buckets = _default_buckets(
            block_size, self.prefill_chunk or self._buckets[-1])
        # context-extent buckets for the chunk program's pool gather:
        # pow2 block counts keep its trace set logarithmic in context
        self._ctx_buckets = _pow2_buckets(self.max_blocks_per_seq)
        self._group_buckets = _pow2_buckets(self.max_batch)
        if prefix_cache is None:
            prefix_cache = env_knobs.get_raw(
                "PADDLE_TPU_PREFIX_CACHE", "0").strip() not in (
                "", "0", "off", "false")
        self.attention_mode = resolve_paged_attention_mode(attention)
        # host-side batch state (authoritative; staged per dispatch)
        self._slots: List[Optional[Request]] = [None] * self.max_batch
        self._tables = np.full((self.max_batch, self.max_blocks_per_seq),
                               SCRATCH_BLOCK, dtype=np.int32)
        self._lengths = np.zeros(self.max_batch, dtype=np.int32)
        # per-slot sampling vectors — decode-step DATA like the page
        # tables, so greedy and sampled requests share one program.
        # Unlike tables/lengths they mutate only at seat/finalize, so
        # the staged device copies are cached and re-staged only on
        # change (4 fewer H2D transfers on every steady-state dispatch)
        self._temps = np.zeros(self.max_batch, dtype=np.float32)
        self._topks = np.zeros(self.max_batch, dtype=np.int32)
        self._topps = np.ones(self.max_batch, dtype=np.float32)
        self._seeds = np.zeros(self.max_batch, dtype=np.uint32)
        # speculative mode stages max_tokens as a [B] data vector too:
        # mid-window truncation is detected ON DEVICE (gen >= maxt),
        # because the host cannot count committed tokens without a sync
        self._maxt = np.zeros(self.max_batch, dtype=np.int32)
        self._samp_dev = None          # invalidated by _mark_sampling
        # reservation-discount knob (opt-in): admission reserves
        # worst-case MINUS live prefix-cache hits; the pinned-block
        # envelope on the allocator keeps the no-OOM invariant — a
        # knob that cannot act must refuse, not no-op
        if prefix_reserve_discount and not prefix_cache:
            raise ValueError(
                "prefix_reserve_discount=True requires "
                "prefix_cache=True: there is nothing to discount "
                "against without the shared-prefix cache")
        self._reserve_discount = bool(prefix_reserve_discount)
        self._prefix = (PrefixCache(
            self._kv.allocator, block_size,
            pin_referenced=self._reserve_discount)
            if prefix_cache else None)
        self._prefill_jobs: deque = deque()
        # disaggregated-tier state: migrations staged OUT (prefill
        # role; the server pump hands them to the router) and the
        # thread-safe inbox of migrations waiting to be imported
        # (decode role; drained on the pump thread as slots and
        # reservations free up)
        self._ready_migrations: deque = deque()
        self._migration_inbox: deque = deque()
        self._mig_lock = threading.Lock()
        self._inbox_need = 0           # reservation estimate of inbox
        self._last_dispatch_t: Optional[float] = None
        # device-resident loop state
        with self._on_device():
            self._tokens = jnp.zeros(self.max_batch, dtype=jnp.int32)
            self._done = jnp.zeros(self.max_batch, dtype=bool)
            if self.spec_k:
                # speculative windows commit a data-dependent token
                # count, so lengths and per-request generated counts
                # ride the loop ON DEVICE; the host `_lengths` becomes
                # an UPPER BOUND (for page growth) reconciled at the
                # whitelisted poll
                self._lengths_dev = jnp.zeros(self.max_batch,
                                              dtype=jnp.int32)
                self._gen = jnp.zeros(self.max_batch, dtype=jnp.int32)
        # committed-token counts already credited to the spec metrics
        # at the last poll (host mirror of `_gen`, poll-delayed)
        self._gen_seen = np.zeros(self.max_batch, dtype=np.int64)
        # committed-token UPPER bound per slot (a window commits at
        # most k+1): while every active lane is provably below its
        # max_tokens the timed poll is skipped outright — a poll is a
        # pipeline-stalling sync, and with no eos id max_tokens is the
        # only way a lane can finish (see step())
        self._gen_ub = np.zeros(self.max_batch, dtype=np.int64)
        # compiled steps (ONE jit each; trace cache keyed by shape —
        # decode must stay at exactly one trace, tests pin it)
        self._decode = self._build_decode_step()
        self._prefill = jax.jit(self._run_prefill)
        self._chunk = jax.jit(self._run_chunk, donate_argnums=(1,))
        self._write = jax.jit(
            lambda pool, kv, blocks: self._write_pages(pool, kv, blocks),
            donate_argnums=(0,))
        # NOT donated: the emitted-token array a join rewrites is still
        # referenced by that dispatch's LazyStack streaming views — a
        # donation would invalidate tokens a consumer has yet to read
        self._join = jax.jit(
            lambda tok, done, i, v: (tok.at[i].set(v),
                                     done.at[i].set(False)))
        # speculative join/clear: one op sets every device-resident
        # per-slot scalar (token, done, length, generated count) so a
        # seat or finalize updates the loop state in ONE dispatch.
        # Same non-donation rationale as _join.
        self._spec_join = jax.jit(
            lambda tok, done, lens, gen, i, v, L, g, d: (
                tok.at[i].set(v), done.at[i].set(d),
                lens.at[i].set(L), gen.at[i].set(g)))
        # page-migration D2D copy ops (DESIGN-SERVING.md
        # §Disaggregated tier): the exporter's pool is NOT donated
        # (other slots still live in it); the importer's is — the
        # scatter output replaces the handle exactly like a decode
        # dispatch.  Trace cache keyed by the pow2 block-count bucket.
        self._export_kv = jax.jit(gather_request_pages)
        self._import_kv = jax.jit(scatter_request_pages,
                                  donate_argnums=(0,))
        self._init_observability()

    def _on_device(self):
        """Placement scope for this engine's device work: under a
        pinned ``device=`` every un-committed ``device_put``/array
        creation in the block lands there (committed inputs already
        carry their placement).  No-op without a pin."""
        return (jax.default_device(self._device)
                if self._device is not None
                else contextlib.nullcontext())

    def _init_observability(self):
        """Per-engine children on the process-wide metrics registry
        (DESIGN-OBSERVABILITY.md): latency/TTFT as fixed-bucket
        histograms, queue depth / occupancy / fragmentation as
        COLLECT-TIME function gauges (zero hot-path cost; weakref so a
        dead engine scrapes as absent, not stale), token/dispatch
        counters on the hot path as plain host adds.  ``LLMServer.
        stats()`` reads these back — the registry is the source of
        truth, the ad-hoc dicts are gone.  Children persist after the
        engine dies (counters/histograms are process-lifetime, like
        any Prometheus client); a churny caller that builds many
        engines reclaims them with :meth:`unregister_metrics`."""
        ordinal = next(_engine_ids)
        self._obs_id = f"e{ordinal}"
        # synthetic-lane base: the process-unique ordinal (not a hash)
        # keys the lane range, so two live engines can never interleave
        # request spans on one track
        self._obs_lane_base = _REQ_LANE_BASE + (ordinal << 16)
        # the phase label keys per-role dashboards: a disaggregated
        # deployment sums decode-phase children for steady-state SLOs
        # and prefill-phase children for admission capacity
        self._obs_labels = {"engine": self._obs_id, "phase": self.role}
        labels = self._obs_labels
        reg = _obs_metrics.registry()
        self._c_dispatches = reg.counter(
            "serving_dispatches_total",
            "batched decode dispatches", labels=labels)
        self._c_tokens = reg.counter(
            "serving_tokens_total",
            "generated tokens (eos-truncated)", labels=labels)
        self._c_requests = reg.counter(
            "serving_requests_completed_total",
            "finalized requests", labels=labels)
        self._h_latency = reg.histogram(
            "serving_latency_s", "request submit→finish latency",
            labels=labels)
        self._h_ttft = reg.histogram(
            "serving_ttft_s", "request submit→first-token latency",
            labels=labels)
        self._h_queue_time = reg.histogram(
            "serving_queue_time_s", "request submit→admission wait",
            labels=labels)
        # long-context tier instruments (DESIGN-SERVING.md
        # §Long-context tier): prefix-cache traffic counters tick at
        # match/insert sites, chunk latency is the host wall around
        # each chunk dispatch (async-dispatch caveat documented there)
        self._c_prefix_hits = reg.counter(
            "serving_prefix_cache_hits_total",
            "prompt blocks reused from the shared-prefix cache",
            labels=labels)
        self._c_prefix_misses = reg.counter(
            "serving_prefix_cache_misses_total",
            "share-eligible prompt blocks prefilled fresh",
            labels=labels)
        self._c_prefix_evictions = reg.counter(
            "serving_prefix_cache_evictions_total",
            "idle prefix entries reclaimed under pool pressure",
            labels=labels)
        self._h_chunk = reg.histogram(
            "serving_prefill_chunk_s",
            "per-chunk prefill dispatch wall time", labels=labels)
        # disaggregated tier (DESIGN-SERVING.md §Disaggregated tier):
        # migration instruments tick on the IMPORTING engine — a
        # migration counts when it is seated into a decode batch, not
        # when it is cut (a parked or failed handoff is not traffic).
        # The inter-token histogram is the decode pool's scaling
        # signal: host wall between consecutive decode dispatches
        # while the batch is non-empty (same async-dispatch caveat as
        # the chunk histogram).
        self._c_migrations = reg.counter(
            "serving_page_migrations_total",
            "migrated requests imported into this engine's batch",
            labels=labels)
        self._c_migrated_blocks = reg.counter(
            "serving_migrated_blocks_total",
            "KV pool blocks imported via page migration",
            labels=labels)
        self._h_migration = reg.histogram(
            "serving_migration_s",
            "prefill-complete to decode-seated handoff wall time",
            labels=labels)
        self._h_intertoken = reg.histogram(
            "serving_intertoken_s",
            "gap between consecutive decode dispatches of a non-empty "
            "batch", labels=labels)
        # speculative tier (DESIGN-SERVING.md §Speculative tier): the
        # dispatch counter ticks on the hot path; tokens/dispatch and
        # the implied acceptance rate are poll-window aggregates
        # computed at the one sanctioned sync (_reconcile_spec) — a
        # per-dispatch accept readout would itself be a sync
        self._c_spec_dispatches = reg.counter(
            "serving_spec_dispatches_total",
            "speculative decode dispatches (k+1-token windows)",
            labels=labels)
        self._h_spec_tpd = reg.histogram(
            "serving_spec_tokens_per_dispatch",
            "committed tokens per active lane per speculative "
            "dispatch (poll-window mean)", labels=labels,
            edges=(0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0,
                   16.0))
        wr = weakref.ref(self)

        def _gauge_fn(getter):
            def fn():
                eng = wr()
                return None if eng is None else getter(eng)
            return fn

        reg.gauge("serving_queue_depth", "waiting requests",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e.scheduler.queue_depth))
        reg.gauge("serving_active", "requests in the running batch",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e.active_count))
        reg.gauge("serving_kv_fragmentation",
                  "KV block-pool fragmentation [0,1]",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e._kv.allocator.stats()
                      ["fragmentation"]))
        reg.gauge("serving_done_poll_interval",
                  "dispatches between EOS polls (auto-tuned)",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e.done_poll_interval))
        # absent (None) while the prefix cache is disabled — a dead
        # series would read as "cache on, empty"
        reg.gauge("serving_prefix_blocks",
                  "pool blocks owned by the shared-prefix cache",
                  labels=labels).set_function(
            _gauge_fn(lambda e: None if e._prefix is None
                      else e._prefix.cached_blocks))
        reg.gauge("serving_prefix_refs",
                  "live request references onto shared prefix blocks",
                  labels=labels).set_function(
            _gauge_fn(lambda e: None if e._prefix is None
                      else e._prefix.live_refs))
        # absent (None) while speculation is off or unmeasured — a
        # dead series would read as "speculating, rejecting all"
        reg.gauge("serving_spec_accept_rate",
                  "draft-token acceptance rate [0,1] implied by the "
                  "cumulative committed tokens per lane-dispatch",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e._spec_accept if e.spec_k else None))
        self._obs_metric_names = (
            "serving_dispatches_total", "serving_tokens_total",
            "serving_requests_completed_total", "serving_latency_s",
            "serving_ttft_s", "serving_queue_time_s",
            "serving_prefix_cache_hits_total",
            "serving_prefix_cache_misses_total",
            "serving_prefix_cache_evictions_total",
            "serving_prefill_chunk_s",
            "serving_page_migrations_total",
            "serving_migrated_blocks_total",
            "serving_migration_s", "serving_intertoken_s",
            "serving_spec_dispatches_total",
            "serving_spec_tokens_per_dispatch",
            "serving_queue_depth", "serving_active",
            "serving_kv_fragmentation", "serving_done_poll_interval",
            "serving_prefix_blocks", "serving_prefix_refs",
            "serving_spec_accept_rate")

    def unregister_metrics(self):
        """Reclaim this engine's labeled children from the process-wide
        registry.  Engine-churn hygiene: children are process-lifetime
        by default (Prometheus semantics), so a caller that builds many
        short-lived engines calls this when an engine is retired to
        keep scrape output and registry memory bounded."""
        reg = _obs_metrics.registry()
        for name in self._obs_metric_names:
            reg.unregister(name, labels=self._obs_labels)

    # -- compiled steps ------------------------------------------------------
    def _run_prefill(self, params, ids, lengths, temps, topks, topps,
                     seeds):
        """Batched same-bucket prefill: ONE dispatch per bucket group
        (trace cache keyed by the (group, bucket) shape pair)."""
        return prefill_group_forward(params, self._cfg, ids, lengths,
                                     temps, topks, topps, seeds)

    def _run_chunk(self, params, pool, ctx_table, ctx_len, ids,
                   chunk_len, chunk_blocks, temp, topk, topp, seed):
        """One prefill chunk against cached context (pool donated);
        trace cache keyed by (chunk bucket, context-extent bucket)."""
        return chunk_prefill_forward(params, self._cfg, pool,
                                     ctx_table, ctx_len, ids,
                                     chunk_len, chunk_blocks, temp,
                                     topk, topp, seed)

    @staticmethod
    def _write_pages(pool, kv, blocks):
        from .kv_cache import write_prompt_pages_group
        return write_prompt_pages_group(pool, kv, blocks)

    def _build_decode_step(self):
        cfg, eos, pad = self._cfg, self.eos_id, self.pad_id
        attn_mode = self.attention_mode
        if self.spec_k:
            return self._build_spec_step(cfg, eos, attn_mode)

        def step(params, pool, table, lengths, tokens, done, temps,
                 topks, topps, seeds):
            active = (lengths > 0) & jnp.logical_not(done)
            pool, logits = decode_forward(params, cfg, pool, table,
                                          lengths, tokens, active,
                                          attention=attn_mode)
            # the sampled token's sequence index is lengths + 1 — the
            # PRNG counter, a pure function of the request (seed,
            # position), never of slot or batch composition
            nxt = sample_tokens(logits, temps, topks, topps, seeds,
                                lengths + 1)
            emit = jnp.where(active, nxt, jnp.int32(pad))
            if eos is not None:
                done = done | (active & (nxt == jnp.int32(eos)))
            return pool, emit, done

        # the decode program is single-trace by contract (fixed
        # [max_batch] geometry; composition changes are DATA): a
        # second trace after dispatch 1 is the silent-retrace class
        # the sentinel exists for
        from ...framework.dispatch import guarded_jit
        return guarded_jit(step, label="serving.decode",
                           single_trace=True, donate_argnums=(1,))

    def _build_spec_step(self, cfg, eos, attn_mode):
        """THE decode program, speculative variant: one compiled
        dispatch proposes, verifies, and commits up to ``k+1`` tokens
        per slot (``spec_decode.py``).  Same single-trace contract and
        label as the plain step — speculation changes what one
        dispatch emits, not how many programs exist.  Completion is
        fully device-detected here (EOS *and* ``gen >= maxt``): the
        host cannot know how many tokens committed without a sync, so
        both ride the ``done`` mask to the poll."""
        k = self.spec_k

        def step(params, dparams, pool, table, lengths, tokens, done,
                 gen, maxt, temps, topks, topps, seeds):
            active = (lengths > 0) & jnp.logical_not(done)
            pool, emit, last, n_emit = spec_decode_step(
                params, dparams, cfg, k, pool, table, lengths,
                tokens, active, temps, topks, topps, seeds,
                attention=attn_mode)
            lengths = jnp.where(active, lengths + n_emit, lengths)
            gen = gen + n_emit
            if eos is not None:
                offs = jnp.arange(k + 1, dtype=jnp.int32)
                valid = offs[None] < n_emit[:, None]
                done = done | (active & jnp.any(
                    valid & (emit == jnp.int32(eos)), axis=1))
            done = done | (active & (gen >= maxt))
            return pool, emit, last, lengths, done, gen

        from ...framework.dispatch import guarded_jit
        return guarded_jit(step, label="serving.decode",
                           single_trace=True, donate_argnums=(2,))

    # -- front door ----------------------------------------------------------
    def submit(self, prompt_ids, max_tokens: int, stream_cb=None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0,
               seed: Optional[int] = None) -> Request:
        """Enqueue a generation request (thread-safe).  Returns the
        :class:`Request`; its ``future`` resolves to a
        :class:`GenerationResult`.  ``temperature``/``top_k``/
        ``top_p``/``seed`` select in-program sampling (0 temperature =
        greedy; see ``sampling.py`` for semantics and the determinism
        contract).  Raises :class:`~.scheduler.QueueFull` at queue
        capacity and ``ValueError`` for requests the pool geometry can
        never run."""
        if self.role == "decode":
            raise ValueError(
                "decode-role engine admits only migrated requests "
                "(submit_migration); route prompts to a prefill "
                "replica — DESIGN-SERVING.md §Disaggregated tier")
        req = Request(prompt_ids, max_tokens, stream_cb=stream_cb,
                      temperature=temperature, top_k=top_k,
                      top_p=top_p, seed=seed)
        if self.prefill_chunk is None and \
                len(req.prompt) > self._buckets[-1]:
            raise ValueError(
                f"prompt length {len(req.prompt)} exceeds the largest "
                f"prefill bucket {self._buckets[-1]}; enable chunked "
                "prefill (prefill_chunk=) for longer prompts")
        return self.scheduler.submit(req)

    def submit_migration(self, mig: PageMigration) -> None:
        """Enqueue a migrated request for import (thread-safe; decode
        and classic roles only).  Refuses what this engine can never
        or currently cannot honor: :class:`MigrationError` for a
        consumed ticket or a pool-geometry mismatch, ``ValueError``
        for a request whose worst case exceeds this pool outright,
        :class:`QueueFull` when batch slots / reservations are
        exhausted — the router's failover signal (next-least-loaded
        decode replica).  The admission-estimate reads here are racy
        against the pump by design: an optimistic accept only parks
        the migration in the inbox until the pump can actually reserve
        — it can never OOM the pool."""
        if self.role == "prefill":
            raise MigrationError(
                "prefill-role engine cannot import migrations: its "
                "program never decodes")
        if mig.consumed:
            raise MigrationError(
                f"migration of request {mig.request.id} already "
                "imported — tickets are single-use")
        mig.check_geometry(self)
        req = mig.request
        need = req.worst_case_blocks(self.block_size, self.spec_k)
        if need > self._kv.allocator.capacity:
            raise ValueError(
                f"migrated request needs {need} blocks worst-case but "
                f"this pool only has {self._kv.allocator.capacity}")
        if len(req.prompt) + req.max_tokens - 1 > self.max_context:
            raise ValueError(
                f"migrated prompt+max_tokens exceeds max context "
                f"{self.max_context}")
        with self._mig_lock:
            if (self.active_count + len(self._migration_inbox)
                    >= self.max_batch):
                raise QueueFull(
                    "decode replica batch full (active + pending "
                    "migrations at max_batch)")
            if not self._kv.allocator.can_reserve(
                    need + self._inbox_need):
                raise QueueFull(
                    "decode replica cannot reserve the migrated "
                    "request's worst-case blocks")
            self._migration_inbox.append(mig)
            self._inbox_need += need

    @property
    def pending_migrations(self) -> int:
        """Migrations accepted but not yet imported (decode-side
        queue depth; the router's load/scaling signal includes it)."""
        with self._mig_lock:
            return len(self._migration_inbox)

    def pop_ready_migrations(self) -> List[PageMigration]:
        """Drain the staged-out migrations a prefill-role step
        produced (pump-thread only; the server hands them to the
        router's prefill→decode transition)."""
        out = list(self._ready_migrations)
        self._ready_migrations.clear()
        return out

    def drain_all_migrations(self) -> List[PageMigration]:
        """Remove every in-flight migration, inbound and staged-out
        (server teardown/failure path — their futures must not be
        stranded)."""
        with self._mig_lock:
            out = list(self._migration_inbox)
            self._migration_inbox.clear()
            self._inbox_need = 0
        out.extend(self._ready_migrations)
        self._ready_migrations.clear()
        return out

    # -- engine loop ---------------------------------------------------------
    def step(self) -> bool:
        """One engine step, by role.  Classic ("both"): admit waiting
        requests, advance at most ONE prefill chunk, then run ONE
        batched decode dispatch — chunked prefill interleaves with the
        running decode batch instead of stalling it behind a
        whole-prompt dispatch.  "prefill": admission + prefill only;
        finished prompts stage out as migrations and the decode
        dispatch never runs.  "decode": import pending migrations,
        then the decode dispatch — no prefill work can ever reach its
        program.  Returns True while there is (or may be) work."""
        with self._on_device():
            return self._step()

    def _step(self) -> bool:
        if self.role == "decode":
            self._drain_migration_inbox()
        else:
            self._admit()
            self._advance_prefill()
            if self.role == "both":
                self._drain_migration_inbox()
        if self.role == "prefill":
            return (self.scheduler.queue_depth > 0
                    or bool(self._prefill_jobs))
        active = [s for s, r in enumerate(self._slots)
                  if r is not None and not getattr(r, "prefilling",
                                                   False)]
        if not active:
            self._last_dispatch_t = None
            return (self.scheduler.queue_depth > 0
                    or bool(self._prefill_jobs)
                    or self.pending_migrations > 0)
        self._grow_pages(active)
        with _obs_trace.span(
                "serving.dispatch",
                args=({"active": len(active)}
                      if _obs_trace.enabled() else None)):
            # async H2D staging of the (tiny) host-authoritative batch
            # layout; the decode dispatch itself never syncs
            table = jax.device_put(self._tables)
            if self.spec_k:
                staged = self._staged_sampling()
                pool, emit, last, lens, done, gen = self._decode(
                    self._params, self._draft_params, self._kv.pool,
                    table, self._lengths_dev, self._tokens,
                    self._done, self._gen, staged[4], staged[0],
                    staged[1], staged[2], staged[3])
            else:
                lengths = jax.device_put(self._lengths)
                temps, topks, topps, seeds = self._staged_sampling()
                pool, emit, done = self._decode(
                    self._params, self._kv.pool, table, lengths,
                    self._tokens, self._done, temps, topks, topps,
                    seeds)
        self._kv.swap_pool(pool)
        self._done = done
        self._c_dispatches.inc()
        stack = LazyStack(emit)        # ONE shared fetch, if read
        now = time.monotonic()
        if self._last_dispatch_t is not None:
            self._h_intertoken.observe(now - self._last_dispatch_t)
        self._last_dispatch_t = now
        if self.spec_k:
            # the window's LAST emitted token feeds back (D2D); the
            # accepted count stays on device — the host pushes a fixed
            # k+1 lazy views per slot (SPEC_SENTINEL beyond the
            # accepted prefix, stripped at finalize/stream read) and
            # advances its page-growth length by the window UPPER
            # BOUND, reconciled to truth at the next poll.  max_tokens
            # completion is device-detected (gen >= maxt), so no host
            # count check here.
            self._tokens = last
            self._lengths_dev, self._gen = lens, gen
            self._c_spec_dispatches.inc()
            self._spec_lanes += len(active)
            for s in active:
                req = self._slots[s]
                for j in range(self.spec_k + 1):
                    req.push_token(
                        LazyScalar(stack,
                                   post=(lambda a, i=s, jj=j:
                                         a[i, jj])), now)
                self._gen_ub[s] += self.spec_k + 1
                if not req.capped:
                    self._lengths[s] += self.spec_k + 1
        else:
            self._tokens = emit        # feeds back next dispatch (D2D)
            to_finish = []
            for s in active:
                req = self._slots[s]
                req.push_token(
                    LazyScalar(stack, post=(lambda a, i=s: a[i])), now)
                if not req.capped:
                    self._lengths[s] += 1
                if len(req.lazy_tokens) >= req.max_tokens:
                    to_finish.append(s)
            for s in to_finish:
                self._finalize(s)
        # speculative mode polls even without an eos id: max_tokens
        # completion only exists on device there.  But with NO eos id,
        # max_tokens is also the ONLY way a lane finishes — and the
        # host holds a committed-count upper bound (`_gen_ub`, +k+1
        # per window, reconciled to truth at each poll), so the
        # reachability gate IS the poll cadence: the sync — a
        # pipeline-stalling host round-trip — fires exactly when some
        # lane may have crossed its cap and is provably a no-op any
        # earlier.  The bound grows every dispatch, so the gate always
        # opens eventually.  With an eos id EOS can end any lane on
        # any dispatch, and the interval cadence stays in charge.
        if self.spec_k and self.eos_id is None:
            if any(self._gen_ub[s] >= self._slots[s].max_tokens
                   for s in active):
                self._timed_poll()
        elif (self.eos_id is not None or self.spec_k) and \
                self._dispatch_count % self.done_poll_interval == 0:
            self._timed_poll()
        return True

    def run_until_idle(self, max_dispatches: int = 100_000):
        """Pump :meth:`step` until queue and batch drain (tests/CLI)."""
        n = 0
        while self.step():
            n += 1
            if n > max_dispatches:
                raise RuntimeError(
                    f"run_until_idle: still busy after {n} dispatches")
        return n

    # -- admission / prefill -------------------------------------------------
    def _alloc_blocks(self, n: int) -> List[int]:
        """Pool draw with prefix-cache pressure relief: idle cached
        entries are the only occupancy beyond the reservation
        envelope, so evicting them (LRU, leaf-first) restores the
        no-OOM guarantee of reservation-gated admission."""
        if self._prefix is not None:
            ev0 = self._prefix.evictions
            self._prefix.ensure_free(n)
            d = self._prefix.evictions - ev0
            if d:
                self._c_prefix_evictions.inc(d)
        return self._kv.allocator.allocate(n)

    def _set_sampling(self, slot: int, req: Request):
        self._temps[slot] = req.temperature
        self._topks[slot] = req.top_k
        self._topps[slot] = req.top_p
        self._seeds[slot] = np.uint32(req.seed & 0xFFFFFFFF)
        self._maxt[slot] = req.max_tokens
        self._samp_dev = None

    def _staged_sampling(self):
        """Device copies of the per-slot sampling vectors (plus the
        max_tokens vector in speculative mode), re-staged only when a
        seat/finalize mutated them."""
        if self._samp_dev is None:
            vecs = [self._temps, self._topks, self._topps, self._seeds]
            if self.spec_k:
                vecs.append(self._maxt)
            self._samp_dev = tuple(jax.device_put(v) for v in vecs)
        return self._samp_dev

    def _cache_insert(self, req: Request, start: int, chain: bytes,
                      blocks: List[int]):
        """Register a prefilled prompt's share-eligible blocks with
        the prefix cache (ownership transfer; the request keeps a
        reference on each new entry and keeps hash-collision
        duplicates as its own)."""
        n_share = self._prefix.shareable_blocks(req.prompt)
        n_insert = max(0, n_share - start)
        if not n_insert:
            return
        entries, _ = self._prefix.insert(req.prompt, start, chain,
                                         blocks[:n_insert])
        req.prefix_entries = req.prefix_entries + entries
        inserted = {e.block for e in entries}
        req.blocks = [b for b in req.blocks if b not in inserted]
        if self._reserve_discount and entries:
            # the freshly inserted blocks are now cache-owned and
            # pinned (this request references them) — keeping them
            # reserved too would double-count them in the envelope
            self.scheduler.release_partial(req, len(entries))

    def _admission_need(self, req: Request) -> int:
        """Blocks to RESERVE for one admission, by role and knob: a
        prefill-role engine reserves prompt blocks only (the decode
        growth is the importing replica's worst case to reserve), and
        reservation-discounted admission subtracts the live
        prefix-cache hit depth — the match is taken HERE (references
        and pins included) so the discount is computed against entries
        that can no longer be evicted, never a stale peek."""
        base = (-(-len(req.prompt) // self.block_size)
                if self.role == "prefill"
                else req.worst_case_blocks(self.block_size,
                                           self.spec_k))
        if not self._reserve_discount or self._prefix is None:
            return base
        entries, chain = self._prefix.match(req.prompt, count=False)
        req._pre_matched = (entries, chain)
        return max(0, base - len(entries))

    def _admission_cancel(self, req: Request):
        """Reservation refused after a discounted-admission match:
        the request stays queued, so the speculative references (and
        their pins) must drop — they are retaken on the next
        attempt."""
        pre = getattr(req, "_pre_matched", None)
        if pre is not None:
            self._prefix.release(pre[0])
            req._pre_matched = None

    def _admit(self):
        """Admit waiting requests: prefix-cache lookup decides the
        prefill path per request — requests continuing from cached
        context (or longer than ``prefill_chunk``) go through the
        chunk machinery; the rest batch into one dispatch per bucket
        group."""
        free = [s for s, r in enumerate(self._slots) if r is None]
        if not free:
            return
        grouped: List = []
        for req in self.scheduler.pop_admissible(
                len(free), need_fn=self._admission_need,
                cancel_fn=self._admission_cancel):
            slot = free.pop(0)
            req.slot = slot
            self._slots[slot] = req
            entries, chain = ([], b"")
            if self._prefix is not None:
                pre = getattr(req, "_pre_matched", None)
                if pre is not None:
                    entries, chain = pre
                    req._pre_matched = None
                    n_share = self._prefix.shareable_blocks(req.prompt)
                    self._prefix.count_match(len(entries),
                                             n_share - len(entries))
                else:
                    entries, chain = self._prefix.match(req.prompt)
                    n_share = self._prefix.shareable_blocks(req.prompt)
                if len(entries):
                    self._c_prefix_hits.inc(len(entries))
                if n_share - len(entries):
                    self._c_prefix_misses.inc(n_share - len(entries))
            long_prompt = (self.prefill_chunk is not None
                           and len(req.prompt) > self.prefill_chunk)
            if entries or long_prompt:
                self._start_chunked(slot, req, entries, chain)
            else:
                grouped.append((slot, req))
        self._prefill_grouped(grouped)

    def _prefill_grouped(self, seated: List):
        """Batched same-bucket prefill: ONE dispatch per bucket group
        (group size padded to a pow2 bucket so the trace set stays
        ``len(buckets) * log2(max_batch)``), one grouped page-write
        dispatch, then per-request seating."""
        if not seated:
            return
        by_bucket: Dict[int, List] = {}
        for slot, req in seated:
            b = shape_bucket(len(req.prompt), self._buckets)
            by_bucket.setdefault(b, []).append((slot, req))
        for bucket, members in sorted(by_bucket.items()):
            G = len(members)
            Gb = shape_bucket(G, self._group_buckets)
            ids = np.zeros((Gb, bucket), dtype=np.int32)
            lengths = np.zeros(Gb, dtype=np.int32)
            temps = np.zeros(Gb, dtype=np.float32)
            topks = np.zeros(Gb, dtype=np.int32)
            topps = np.ones(Gb, dtype=np.float32)
            seeds = np.zeros(Gb, dtype=np.uint32)
            for g, (slot, req) in enumerate(members):
                Lp = len(req.prompt)
                ids[g, :Lp] = req.prompt
                lengths[g] = Lp
                temps[g] = req.temperature
                topks[g] = req.top_k
                topps[g] = req.top_p
                seeds[g] = np.uint32(req.seed & 0xFFFFFFFF)
            with _obs_trace.span(
                    "serving.prefill",
                    args=({"bucket": bucket, "group": G}
                          if _obs_trace.enabled() else None)):
                kv, toks, _ = self._prefill(
                    self._params, jax.device_put(ids),
                    jax.device_put(lengths), jax.device_put(temps),
                    jax.device_put(topks), jax.device_put(topps),
                    jax.device_put(seeds))
            nb_bucket = bucket // self.block_size
            blocks_arr = np.full((Gb, nb_bucket), SCRATCH_BLOCK,
                                 dtype=np.int32)
            per_req_blocks = []
            for g, (slot, req) in enumerate(members):
                nb_needed = self._kv.blocks_for_tokens(len(req.prompt))
                blocks = self._alloc_blocks(nb_needed)
                blocks_arr[g, :nb_needed] = blocks
                per_req_blocks.append(blocks)
            self._kv.swap_pool(self._write(self._kv.pool, kv,
                                           jax.device_put(blocks_arr)))
            stack = LazyStack(toks)
            now = time.monotonic()
            for g, (slot, req) in enumerate(members):
                self._seat(slot, req, per_req_blocks[g], toks[g],
                           LazyScalar(stack, post=(lambda a, i=g: a[i])),
                           now)

    def _join_loop(self, slot: int, tok_dev, length: int, gen: int):
        """Join a seated request into the device loop state: token and
        done flag in classic mode, plus device length and generated
        count in speculative mode (where both ride the loop)."""
        if self.spec_k:
            (self._tokens, self._done, self._lengths_dev,
             self._gen) = self._spec_join(
                self._tokens, self._done, self._lengths_dev,
                self._gen, np.int32(slot), tok_dev, np.int32(length),
                np.int32(gen), np.bool_(False))
            self._gen_seen[slot] = gen
            self._gen_ub[slot] = gen
        else:
            self._tokens, self._done = self._join(
                self._tokens, self._done, np.int32(slot), tok_dev)

    def _spec_clear(self, slot: int):
        """Kill a slot in the speculative device loop (finalize and
        re-enter-prefill paths): done=True, length 0.  Classic mode
        needs no analogue — it stages host lengths every dispatch, so
        zeroing ``_lengths[slot]`` deactivates the lane; speculative
        lengths live on device and a stale positive value would run
        the dead lane as active."""
        (self._tokens, self._done, self._lengths_dev,
         self._gen) = self._spec_join(
            self._tokens, self._done, self._lengths_dev, self._gen,
            np.int32(slot), jnp.int32(0), np.int32(0), np.int32(0),
            np.bool_(True))
        self._gen_seen[slot] = 0
        self._gen_ub[slot] = 0

    def _seat(self, slot: int, req: Request, blocks: List[int],
              tok_dev, first_tok, now: float):
        """Seat a fully prefilled request in the decode batch: page
        table, sampling vectors, prefix-cache insertion of its full
        prompt blocks, and the prefill-emitted first token."""
        Lp = len(req.prompt)
        nb = len(blocks)
        req.blocks = list(blocks)
        start = req.n_prefix_blocks
        self._tables[slot, start + nb:] = SCRATCH_BLOCK
        self._tables[slot, start:start + nb] = blocks
        self._lengths[slot] = Lp
        if self._prefix is not None:
            self._cache_insert(req, start,
                               getattr(req, "_prefix_chain", b""),
                               list(req.blocks))
        req.prefilling = False
        if self.role == "prefill":
            # phase boundary: token 0 streams from HERE (TTFT is the
            # prefill replica's); the rest of the request leaves as a
            # migration — this engine's decode program never runs
            req.push_token(first_tok, now)
            if req.max_tokens == 1:
                self._finalize(slot)
            else:
                self._stage_handoff(slot, req, tok_dev, now)
            return
        self._set_sampling(slot, req)
        self._join_loop(slot, tok_dev, Lp, 1)
        req.push_token(first_tok, now)
        if req.max_tokens == 1:
            self._finalize(slot)

    def _start_chunked(self, slot: int, req: Request, entries, chain):
        """Enter the chunk-prefill path: seat the prefix-cache hits in
        the page table now (their K/V are already in the pool) and
        queue the remainder of the prompt for chunkwise admission
        interleaved with the decode loop."""
        req.prefilling = True
        req.prefix_entries = list(entries)
        req._prefix_chain = chain
        ctx_len = len(entries) * self.block_size
        self._tables[slot, :] = SCRATCH_BLOCK
        self._tables[slot, :len(entries)] = [e.block for e in entries]
        self._lengths[slot] = 0            # joins decode at completion
        if self.spec_k:
            self._spec_clear(slot)         # predecessor's device state
        self._prefill_jobs.append(
            _PrefillJob(req, slot, chain, ctx_len, len(entries)))

    def _advance_prefill(self):
        """Run at most ONE chunk of the head prefill job — the fixed
        unit of prefill work an engine step may spend, so a 32k prompt
        admits over many steps while the decode batch keeps
        dispatching between chunks."""
        if not self._prefill_jobs:
            return
        job = self._prefill_jobs[0]
        req, slot = job.req, job.slot
        Lp = len(req.prompt)
        remaining = Lp - job.done_tokens
        take = min(remaining, self.prefill_chunk or remaining)
        bs = self.block_size
        Cb = shape_bucket(take, self._chunk_buckets)
        # chunk starts are block-aligned (prefix hits and full chunks
        # are block multiples), so the new-block count is exact
        nb_new = -(-take // bs)
        new_blocks = self._alloc_blocks(nb_new)
        job.computed_blocks.extend(new_blocks)
        req.blocks.extend(new_blocks)
        have = req.n_prefix_blocks + len(req.blocks)
        self._tables[slot, have - nb_new:have] = new_blocks
        chunk_blocks = np.full(Cb // bs, SCRATCH_BLOCK, dtype=np.int32)
        chunk_blocks[:nb_new] = new_blocks
        nb_ctx = shape_bucket(max(1, job.done_tokens // bs),
                              self._ctx_buckets)
        ctx_table = np.ascontiguousarray(
            self._tables[slot:slot + 1, :nb_ctx])
        ids = np.zeros((1, Cb), dtype=np.int32)
        ids[0, :take] = req.prompt[job.done_tokens:job.done_tokens + take]
        t0 = time.monotonic()
        with _obs_trace.span(
                "serving.prefill_chunk",
                args=({"chunk": take, "ctx": job.done_tokens,
                       "bucket": Cb} if _obs_trace.enabled()
                      else None)):
            pool, tok, _ = self._chunk(
                self._params, self._kv.pool, jax.device_put(ctx_table),
                np.int32(job.done_tokens), jax.device_put(ids),
                np.int32(take), jax.device_put(chunk_blocks),
                np.float32(req.temperature), np.int32(req.top_k),
                np.float32(req.top_p),
                np.uint32(req.seed & 0xFFFFFFFF))
        self._kv.swap_pool(pool)
        self._h_chunk.observe(time.monotonic() - t0)
        job.done_tokens += take
        if job.done_tokens < Lp:
            return
        # prompt complete: cache-insert its freshly computed full
        # blocks, seat the slot in the decode batch, emit token 0
        self._prefill_jobs.popleft()
        if self._prefix is not None:
            self._cache_insert(req, job.insert_from, job.chain,
                               job.computed_blocks)
        self._lengths[slot] = Lp
        req.prefilling = False
        now = time.monotonic()
        if self.role == "prefill":
            req.push_token(LazyScalar(tok), now)
            if req.max_tokens == 1:
                self._finalize(slot)
            else:
                self._stage_handoff(slot, req, tok, now)
            return
        self._set_sampling(slot, req)
        self._join_loop(slot, tok, Lp, 1)
        req.push_token(LazyScalar(tok), now)
        if req.max_tokens == 1:
            self._finalize(slot)

    def _grow_pages(self, active: List[int]):
        """Append-allocate blocks for requests whose upcoming writes
        cross a block boundary.  Reservation-gated admission
        guarantees success within ``req.reserved_blocks``; a slot at
        its budget is a device-done request the host has not polled
        yet — growth (and length advance) stop, its masked writes land
        in scratch.

        Speculative mode covers the whole look-ahead window: the next
        dispatch writes positions up to ``length + k`` where the host
        length is an UPPER BOUND on the device truth, so coverage of
        the bound covers every real write; the window's uncommitted
        tail is inside the ``lookahead``-widened budget the scheduler
        reserved, so rejection churn can never OOM the pool.  May
        allocate several blocks per dispatch (the window can cross
        more than one boundary)."""
        look = self.spec_k
        for s in active:
            req = self._slots[s]
            if req.capped:
                continue
            have = req.n_prefix_blocks + len(req.blocks)
            need = self._kv.blocks_for_tokens(
                int(self._lengths[s]) + 1, lookahead=look)
            while have < need:
                if have >= req.block_budget or \
                        have >= self.max_blocks_per_seq:
                    req.capped = True
                    break
                blk = self._alloc_blocks(1)[0]
                req.blocks.append(blk)
                self._tables[s, have] = blk
                have += 1

    # -- page migration (disaggregated tier) ---------------------------------
    def _stage_handoff(self, slot: int, req: Request, tok_dev,
                       now: float):
        """Cut the migration ticket for a prompt this prefill-role
        engine just finished: gather the request's pages in TABLE
        order (prefix-cache hits first, then its own blocks — the
        order the decode attention reads them), free the source copy,
        and stage the ticket for the router's prefill→decode
        transition.  Token 0 already streamed from here; the device
        token rides the ticket as the decode replica's next-dispatch
        input, never re-pushed."""
        nb_total = req.n_prefix_blocks + len(req.blocks)
        nbb = shape_bucket(nb_total, self._ctx_buckets)
        ids = np.full(nbb, SCRATCH_BLOCK, dtype=np.int32)
        ids[:nb_total] = self._tables[slot, :nb_total]
        with _obs_trace.span(
                "serving.export",
                args=({"blocks": nb_total}
                      if _obs_trace.enabled() else None)):
            kv = self._export_kv(self._kv.pool, jax.device_put(ids))
        kvc = self._kv
        mig = PageMigration(
            req, kv, nb_total, tok_dev, now,
            geometry={"num_layers": kvc.num_layers,
                      "block_size": kvc.block_size,
                      "num_heads": kvc.num_heads,
                      "head_dim": kvc.head_dim,
                      "dtype": str(kvc.pool.dtype)},
            source=self._obs_id)
        # the ticket owns the K/V now: free the source copy.  Shared
        # prefix blocks stay cached HERE, warm for the next prompt —
        # only this request's reference drops; its exclusive blocks
        # export (free + lifetime counter) back to this pool.
        self.scheduler.finish(req)
        if req.blocks:
            self._kv.allocator.export_blocks(req.blocks)
            req.blocks = []
        if req.prefix_entries:
            self._prefix.release(req.prefix_entries)
            req.prefix_entries = []
        self._slots[slot] = None
        self._lengths[slot] = 0
        self._tables[slot, :] = SCRATCH_BLOCK
        self._ready_migrations.append(mig)

    def _drain_migration_inbox(self):
        """Seat accepted migrations as slots and reservations free up
        (pump thread only).  Strict FIFO with head blocking, mirroring
        the scheduler's admission policy: a large migrated request at
        the head waits for capacity, it is never overtaken."""
        while True:
            with self._mig_lock:
                if not self._migration_inbox:
                    return
                mig = self._migration_inbox[0]
            slot = next((s for s, r in enumerate(self._slots)
                         if r is None), None)
            if slot is None:
                return
            need = mig.request.worst_case_blocks(self.block_size,
                                                 self.spec_k)
            if not self._kv.allocator.reserve(need):
                return
            with self._mig_lock:
                self._migration_inbox.popleft()
                self._inbox_need -= need
            try:
                self._import_migration(slot, mig, need)
            except MigrationError:
                # consumed ticket (double submit): whoever imported it
                # first owns the request; drop ours and keep draining
                self._kv.allocator.release(need)

    def _import_migration(self, slot: int, mig: PageMigration,
                          need: int):
        """Land one migrated request in this engine's batch: fresh
        pool blocks, one D2D scatter of the ticket's K/V, page-table
        remap to THIS pool's ids, sampling state, and the device
        token joined as the next dispatch's input.  Token-exact
        continuation: length, resolved seed, and token all came over,
        so the first decode dispatch here samples exactly the position
        the single-engine oracle would."""
        req = mig.consume()
        nb = mig.nb
        # the ticket's arrays are committed on the EXPORTER's device;
        # under a placement pin they must cross explicitly before the
        # scatter/join (the in-process analogue of the multi-host
        # fleet-KV fetch — DESIGN-SERVING.md §Multi-host sketch)
        mig_kv, mig_tok = mig.kv, mig.token
        if self._device is not None:
            mig_kv = jax.device_put(mig_kv, self._device)
            mig_tok = jax.device_put(mig_tok, self._device)
        if self._prefix is not None:
            ev0 = self._prefix.evictions
            self._prefix.ensure_free(nb)
            d = self._prefix.evictions - ev0
            if d:
                self._c_prefix_evictions.inc(d)
        blocks = self._kv.allocator.import_blocks(nb)
        # scatter ids pad to the TICKET's bucket (the gathered kv's
        # block extent), not this engine's — the tail lands in scratch
        nbb = int(mig.kv.shape[2])
        ids = np.full(nbb, SCRATCH_BLOCK, dtype=np.int32)
        ids[:nb] = blocks
        with _obs_trace.span(
                "serving.import",
                args=({"blocks": nb}
                      if _obs_trace.enabled() else None)):
            self._kv.swap_pool(self._import_kv(
                self._kv.pool, mig_kv, jax.device_put(ids)))
        req.slot = slot
        self._slots[slot] = req
        req.blocks = list(blocks)
        req.prefix_entries = []
        req.reserved_blocks = need
        req.block_budget = req.worst_case_blocks(self.block_size,
                                                 self.spec_k)
        Lp = len(req.prompt)
        self._tables[slot, :] = SCRATCH_BLOCK
        self._tables[slot, :nb] = blocks
        self._lengths[slot] = Lp
        self._set_sampling(slot, req)
        if self._prefix is not None:
            # the imported prompt blocks are ordinary full-prompt
            # blocks in THIS pool now — register them so later local
            # prompts (and the discount envelope) can share them
            self._cache_insert(req, 0, b"", list(req.blocks))
        req.prefilling = False
        # gen carries the tokens already streamed on the prefill side
        # (token 0), so max_tokens truncation stays exact across the
        # phase boundary
        self._join_loop(slot, mig_tok, Lp, len(req.lazy_tokens))
        self._c_migrations.inc()
        self._c_migrated_blocks.inc(nb)
        self._h_migration.observe(time.monotonic() - mig.t_start)

    # -- completion ----------------------------------------------------------
    def _timed_poll(self):
        """Auto-tune wrapper around the poll site (auto mode only;
        a fixed explicit interval, or a decided one, goes straight to
        the poll).  While calibrating it polls TWICE: the first poll's
        wall time is dominated by draining the queued dispatch chain —
        that is device compute the loop pays either way, not poll
        overhead — so the cost fed to the tuner is the SECOND,
        empty-chain poll (pure sync + [B] fetch; ``_poll_done`` is
        idempotent).  The per-dispatch unit time is the full inter-poll
        wall including the drain.  The shared
        :class:`~paddle_tpu.framework.dispatch.AutoFoldTuner` then
        freezes ``done_poll_interval`` at the smallest cadence whose
        amortized sync overhead is at most ``target`` of the dispatch
        time — a device-bound loop correctly stays near the tight
        cadence instead of saturating at the bound, keeping the
        EOS→reclaim occupancy loss small (DESIGN-SERVING.md §EOS)."""
        tuner = self._poll_tuner
        if self.spec_k and self.eos_id is None:
            # gated mode (see step()): the reachability gate is the
            # cadence and the tuned interval is never consulted — the
            # calibration's second, empty-chain poll would be a pure
            # wasted sync
            self._poll_done()
            return
        if tuner is None or tuner.decided:
            self._poll_done()
            return
        t0 = time.monotonic()
        self._poll_done()            # drains the in-flight chain
        t1 = time.monotonic()
        self._poll_done()            # chain empty: pure poll cost
        t2 = time.monotonic()
        n = self._dispatch_count - self._last_poll_dispatches
        if self._last_poll_end is not None and n > 0:
            tuner.observe(1, t2 - t1, (t1 - self._last_poll_end) / n)
        else:
            # first poll: compile/warmup-shaped, tuner discards it
            tuner.observe(1, t2 - t1, t1 - t0)
        self._last_poll_end = t2
        self._last_poll_dispatches = self._dispatch_count
        if tuner.decided:
            self.done_poll_interval = tuner.fold
            d = tuner.decision
            self._poll_decision = {
                "done_poll_interval": self.done_poll_interval,
                "poll_cost_ms": d["host_ms_per_step"],
                "dispatch_ms": d["device_ms_per_step"],
                "target": d["overhead_target"],
                "max": d["max_fold"],
            }

    def _poll_done(self):
        """THE group-boundary sync: fetch the [B] device done-mask so
        EOS'd (and, speculatively, max_tokens'd) requests free their
        slot/pages.  Speculative mode widens the SAME fetch to one
        ``device_get`` of (done, lengths, gen) — still one sync at the
        same cadence — because committed lengths and token counts only
        exist on device there: the host reconciles its upper-bound
        lengths to truth and credits the spec metrics from the gen
        deltas.  Runs every ``done_poll_interval`` dispatches, never
        inside one."""
        with _obs_trace.span("serving.poll"):
            if self.spec_k:
                done, lens, gen = jax.device_get(
                    (self._done, self._lengths_dev, self._gen))
                done = np.asarray(done)
                self._reconcile_spec(np.asarray(lens),
                                     np.asarray(gen))
            else:
                done = np.asarray(jax.device_get(self._done))
        for s, req in enumerate(self._slots):
            # a chunk-prefilling slot has not joined the device loop
            # yet: its done flag is its dead predecessor's leftover
            # (reset by _join at seating), never this request's state
            if req is not None and bool(done[s]) and \
                    not getattr(req, "prefilling", False):
                self._finalize(s)

    def _reconcile_spec(self, lens: np.ndarray, gen: np.ndarray):
        """Fold one poll's device truth back into host bookkeeping:
        page-growth lengths drop from upper bound to actual (freeing
        over-advance before it costs an unneeded block), and the spec
        instruments observe the poll window — committed tokens per
        active lane per dispatch (histogram, per window) and the
        implied draft acceptance rate (gauge, CUMULATIVE over the
        engine's life): a live lane commits ``1 + accept*k`` tokens
        per window, so the rate is ``(tokens/lane-dispatch - 1) / k``.
        Lanes the device finished mid-window commit fewer — the
        done-lag drag every occupancy number in this engine shares."""
        emitted = 0
        for s, req in enumerate(self._slots):
            if req is None or getattr(req, "prefilling", False):
                continue
            self._lengths[s] = lens[s]
            # the poll sits at a dispatch boundary, so the fetched gen
            # IS the current truth: the upper bound snaps down to it
            # and the poll gate in step() re-arms
            self._gen_ub[s] = int(gen[s])
            d = int(gen[s]) - int(self._gen_seen[s])
            if d > 0:
                emitted += d
                self._gen_seen[s] = int(gen[s])
        nd = self._spec_lanes - self._spec_last_poll_lanes
        self._spec_emitted += emitted
        if nd > 0:
            self._h_spec_tpd.observe(emitted / nd)
        if self._spec_lanes > 0:
            tpd_cum = self._spec_emitted / self._spec_lanes
            self._spec_accept = max(
                0.0, min(1.0, (tpd_cum - 1.0) / self.spec_k))
        self._spec_last_poll_lanes = self._spec_lanes

    def _finalize(self, slot: int):
        """Consumer-boundary materialization: the request is leaving —
        resolving its future IS the read, so the (single, shared per
        dispatch-stack) D2H transfers are sanctioned here."""
        req = self._slots[slot]
        toks = [int(t) for t in req.lazy_tokens]
        if self.spec_k:
            # strip rejected-position sentinels, then clip the final
            # window's overshoot: the device stops AFTER the window
            # that crosses max_tokens, so up to k bonus tokens beyond
            # the cap were committed (and streamed — api.py documents
            # the stream-side contract) and drop here
            toks = [t for t in toks if t != SPEC_SENTINEL]
            toks = toks[:req.max_tokens]
        if self.eos_id is not None and self.eos_id in toks:
            toks = toks[:toks.index(self.eos_id) + 1]
        req.stats.finished = time.monotonic()
        req.stats.generated = len(toks)
        self.scheduler.finish(req)
        if req.blocks:
            self._kv.allocator.free(req.blocks)
            req.blocks = []
        if req.prefix_entries:
            # shared blocks stay cached (idle, warm for the next hit);
            # only the live reference drops
            self._prefix.release(req.prefix_entries)
            req.prefix_entries = []
        self._slots[slot] = None
        self._lengths[slot] = 0
        self._tables[slot, :] = SCRATCH_BLOCK
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        self._topps[slot] = 1.0
        self._seeds[slot] = 0
        self._maxt[slot] = 0
        self._samp_dev = None
        if self.spec_k:
            self._spec_clear(slot)
        self._observe_finalize(slot, req, len(toks))
        req.future.set_result(
            GenerationResult(req.id, toks, req.stats))

    def _observe_finalize(self, slot: int, req: Request, n_toks: int):
        """Registry + timeline record of one finished request.  The
        lifecycle spans (queued→prefill→decode-groups→done) are
        reconstructed RETROACTIVELY from the RequestStats milestones —
        same monotonic clock as the live spans — on a synthetic
        per-slot track, so Perfetto shows concurrent requests as
        parallel lanes without the hot loop carrying span objects."""
        st = req.stats
        self._c_requests.inc()
        self._c_tokens.inc(n_toks)
        if st.latency is not None:
            self._h_latency.observe(st.latency)
        if st.ttft is not None:
            self._h_ttft.observe(st.ttft)
        if st.queue_time is not None:
            self._h_queue_time.observe(st.queue_time)
        if not _obs_trace.enabled():
            return
        lane = self._obs_lane_base + slot
        _obs_trace.set_track_name(
            lane, f"serving-{self._obs_id}-slot{slot}")
        args = {"request_id": req.id, "prompt_len": st.prompt_len,
                "generated": st.generated}
        _obs_trace.add_span("request", st.submitted, st.finished,
                            tid=lane, args=args)
        if st.admitted is not None:
            _obs_trace.add_span("request.queued", st.submitted,
                                st.admitted, tid=lane)
            if st.first_token is not None:
                _obs_trace.add_span("request.prefill", st.admitted,
                                    st.first_token, tid=lane)
                _obs_trace.add_span("request.decode-groups",
                                    st.first_token, st.finished,
                                    tid=lane)

    # -- warmup / stats ------------------------------------------------------
    def warmup(self, prompt_lengths: Optional[Sequence[int]] = None
               ) -> Dict[str, float]:
        """Ahead-of-time compile of the serving programs (ROADMAP
        "cold-start as a product metric"): every prefill bucket the
        given prompt lengths touch (default: all buckets), the page
        writer, the join op, and THE decode step.  Returns wall-times;
        call before traffic cuts over — this is the one engine method
        allowed to block on device completion."""
        with self._on_device():
            return self._warmup(prompt_lengths)

    def _warmup(self, prompt_lengths=None) -> Dict[str, float]:
        t0 = time.monotonic()
        buckets = (sorted({shape_bucket(int(n), self._buckets)
                           for n in prompt_lengths})
                   if prompt_lengths else list(self._buckets))
        per_bucket = {}
        one_f = np.zeros(1, dtype=np.float32)
        one_i = np.zeros(1, dtype=np.int32)
        one_u = np.zeros(1, dtype=np.uint32)
        one_p = np.ones(1, dtype=np.float32)
        for b in buckets:
            tb = time.monotonic()
            ids = np.zeros((1, b), dtype=np.int32)
            kv, tok, _ = self._prefill(
                self._params, jax.device_put(ids),
                jax.device_put(np.ones(1, dtype=np.int32)),
                jax.device_put(one_f), jax.device_put(one_i),
                jax.device_put(one_p), jax.device_put(one_u))
            blocks_arr = np.full((1, b // self.block_size),
                                 SCRATCH_BLOCK, dtype=np.int32)
            self._kv.swap_pool(self._write(self._kv.pool, kv,
                                           jax.device_put(blocks_arr)))
            jax.block_until_ready(tok)
            per_bucket[b] = round(time.monotonic() - tb, 4)
        td = time.monotonic()
        if self.spec_k:
            # all-inactive warm dispatch (device lengths are zero):
            # compiles the full draft+verify window without touching
            # loop semantics; warms the spec join op too
            self._join_loop(0, jnp.int32(0), 0, 0)
            staged = self._staged_sampling()
            pool, emit, last, lens, done, gen = self._decode(
                self._params, self._draft_params, self._kv.pool,
                jax.device_put(self._tables), self._lengths_dev,
                self._tokens, self._done, self._gen, staged[4],
                staged[0], staged[1], staged[2], staged[3])
            self._kv.swap_pool(pool)
            self._tokens, self._done = last, done
            self._lengths_dev, self._gen = lens, gen
            jax.block_until_ready(last)
        else:
            self._tokens, self._done = self._join(
                self._tokens, self._done, np.int32(0), jnp.int32(0))
            w_temps, w_topks, w_topps, w_seeds = self._staged_sampling()
            pool, emit, done = self._decode(
                self._params, self._kv.pool,
                jax.device_put(self._tables),
                jax.device_put(self._lengths), self._tokens,
                self._done, w_temps, w_topks, w_topps, w_seeds)
            self._kv.swap_pool(pool)
            self._tokens, self._done = emit, done
            jax.block_until_ready(emit)
        decode_s = time.monotonic() - td
        return {"warmup_s": round(time.monotonic() - t0, 4),
                "decode_compile_s": round(decode_s, 4),
                "prefill_bucket_s": per_bucket,
                "buckets": buckets}

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def _dispatch_count(self) -> int:
        """Dispatch count read back from the registry counter — the ONE
        copy of this state (stats(), poll cadence, tuner deltas).
        Always incremented with host ints, so the host-only read is
        exact and sync-free."""
        return int(self._c_dispatches.collect(materialize=False))

    def compile_stats(self) -> Dict[str, int]:
        """Recompile-pin introspection (mirrors Model.compile_stats):
        ``decode_traces`` MUST stay 1 across any join/leave pattern."""
        def _size(fn):
            try:
                return fn._cache_size()
            except Exception:
                return -1
        return {"decode_traces": _size(self._decode),
                "prefill_traces": _size(self._prefill),
                "chunk_traces": _size(self._chunk),
                "write_traces": _size(self._write),
                "join_traces": _size(self._join),
                "export_traces": _size(self._export_kv),
                "import_traces": _size(self._import_kv)}

    def stats(self) -> Dict[str, object]:
        st = {"active": self.active_count,
              "role": self.role,
              "queue_depth": self.scheduler.queue_depth,
              "pending_migrations": self.pending_migrations,
              "dispatches": self._dispatch_count,
              "total_tokens": int(
                  self._c_tokens.collect(materialize=False)),
              "done_poll_interval": self.done_poll_interval,
              "attention": self.attention_mode,
              "prefill_chunk": self.prefill_chunk,
              "kv": self._kv.allocator.stats()}
        if self.spec_k:
            st["spec"] = {
                "k": self.spec_k,
                "dispatches": int(self._c_spec_dispatches.collect(
                    materialize=False)),
                "accept_rate": self._spec_accept,
            }
        if self._prefix is not None:
            st["prefix_cache"] = self._prefix.stats()
        if self._poll_decision is not None:
            st["done_poll_decision"] = dict(self._poll_decision)
        st.update(self.compile_stats())
        return st
