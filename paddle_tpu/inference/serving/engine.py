"""Continuous-batching decode engine: one persistent compiled dispatch.

The serving hot loop is the training hot loop's design transplanted to
decode (DESIGN-PERF.md → DESIGN-SERVING.md): device-resident state,
donated through a cached compiled step, with host work strictly
bookkeeping-shaped and *zero* device→host syncs outside two
whitelisted points (``scripts/check_host_sync.py`` guards this module
like it guards ``Model.fit``).

Shape-stability is the whole game (arxiv 2604.15464): the decode
program is compiled ONCE for the engine's geometry —

    (params, pool [L,2,NB,BS,H,Dh], table [B,MAXNB], lengths [B],
     tokens [B], done [B]) -> (pool, tokens, done)

Requests joining and leaving the running batch mutate page-table
*data* between dispatches, never a traced shape, so membership churn
costs no recompiles (test-pinned).  The KV pool is donated and rides
the dispatch chain; emitted tokens feed back as the next dispatch's
input entirely on device; per-token streaming hands consumers
``LazyScalar`` views of a shared per-dispatch ``LazyStack`` — one D2H
transfer per dispatch, only if somebody actually reads.

Prefill runs per request at bucketed prompt lengths
(``io/bucketing.shape_bucket``) through one jit whose trace cache
holds one entry per bucket — the bounded compile set the bucketing
module exists for.

EOS is detected ON DEVICE (``done`` rides the loop); the host learns
of it at ``done_poll_interval`` dispatch boundaries via the single
sanctioned ``_poll_done`` sync.  Between EOS and poll a finished
request wastes masked lanes — the classic poll-cadence/occupancy
trade-off, see DESIGN-SERVING.md §EOS.  The interval is AUTO-TUNED by
default from observed dispatch economics, exactly like the training
engine's fold factor (``framework.dispatch.AutoFoldTuner``): the
first few polls measure the PURE poll cost (an empty-chain poll —
queue-drain time is device compute, not poll overhead) and the
amortized per-dispatch wall time, then the interval is frozen at the
smallest value whose amortized poll overhead is at most
``PADDLE_TPU_SERVING_POLL_TARGET`` (default 5%) of the dispatch
time, bounded by ``PADDLE_TPU_SERVING_POLL_MAX`` (default 64).  An
explicit ``done_poll_interval=`` stays fixed.
"""

from __future__ import annotations

import itertools
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.lazy import LazyScalar, LazyStack
from ...io.bucketing import shape_bucket
from ...observability import metrics as _obs_metrics
from ...observability import trace as _obs_trace
from .decode_model import (ServingModelConfig, decode_forward,
                           extract_decode_params, prefill_forward)
from .kv_cache import SCRATCH_BLOCK, PagedKVCache
from .scheduler import Request, Scheduler

# synthetic Chrome-trace track ids for request lifecycle spans: one
# lane per (engine, batch slot), so concurrent requests render as
# parallel tracks instead of interleaving on the pump thread's row
_REQ_LANE_BASE = 1 << 40
_engine_ids = itertools.count()


class GenerationResult:
    """Resolved value of a request future."""

    __slots__ = ("request_id", "tokens", "stats")

    def __init__(self, request_id, tokens, stats):
        self.request_id = request_id
        self.tokens = tokens            # List[int], eos-truncated
        self.stats = stats              # RequestStats

    def __repr__(self):
        return (f"GenerationResult(id={self.request_id}, "
                f"tokens={self.tokens})")


def _default_buckets(block_size: int, max_context: int) -> List[int]:
    """Power-of-two block multiples up to the context limit — few
    compiles, <= 2x padding waste per prompt.  The top bucket floors
    to a block multiple: a model whose max_position is not one (e.g.
    1000 with 16-token blocks) caps prompts at the floored length
    instead of failing the engine's bucket-alignment check."""
    top = (max_context // block_size) * block_size
    buckets, b = [], block_size
    while b < top:
        buckets.append(b)
        b *= 2
    if not buckets or buckets[-1] != top:
        buckets.append(top)
    return buckets


class DecodeEngine:
    """Continuous-batching decode over a paged KV pool.

    Drive it directly (``submit`` + ``step`` / ``run_until_idle``) or
    through :class:`~paddle_tpu.inference.serving.api.LLMServer`'s
    pump thread.  All methods except ``submit`` must be called from
    ONE thread (the pump); ``submit`` is safe from anywhere.
    """

    def __init__(self, network=None, *, gpt_config=None, params=None,
                 max_batch: int = 4, block_size: int = 16,
                 num_blocks: int = 128,
                 max_blocks_per_seq: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 done_poll_interval: Optional[int] = None,
                 max_queue: int = 64):
        if network is not None:
            params = extract_decode_params(network)
            gpt_config = network.config
        if params is None or gpt_config is None:
            raise ValueError("need network= or (params=, gpt_config=)")
        self._cfg = (gpt_config
                     if isinstance(gpt_config, ServingModelConfig)
                     else ServingModelConfig.from_gpt_config(gpt_config))
        self._params = params
        cfg = self._cfg
        self.max_batch = int(max_batch)
        self.block_size = int(block_size)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        # None = auto-tune the poll cadence from measured dispatch
        # economics — the SAME calibrate/median/clamp policy as the
        # training engine's fold factor (AutoFoldTuner): start at 8,
        # calibrate over the first few polls, freeze
        from ...framework.dispatch import (AutoFoldTuner, _env_float,
                                           _env_int)
        self._poll_auto = done_poll_interval is None
        self.done_poll_interval = (8 if self._poll_auto
                                   else max(1, int(done_poll_interval)))
        self._poll_tuner = (AutoFoldTuner(
            target=_env_float("PADDLE_TPU_SERVING_POLL_TARGET", 0.05),
            max_fold=_env_int("PADDLE_TPU_SERVING_POLL_MAX", 64),
            calib_groups=_env_int("PADDLE_TPU_SERVING_POLL_CALIB", 3))
            if self._poll_auto else None)
        self._poll_decision: Optional[Dict] = None
        self._last_poll_end: Optional[float] = None
        self._last_poll_dispatches = 0
        if max_blocks_per_seq is None:
            max_blocks_per_seq = -(-cfg.max_position // block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.max_context = min(cfg.max_position,
                               self.max_blocks_per_seq * block_size)
        dtype = params["wte"].dtype
        self._kv = PagedKVCache(cfg.num_layers, num_blocks, block_size,
                                cfg.num_heads, cfg.head_dim, dtype=dtype)
        self.scheduler = Scheduler(self._kv.allocator, block_size,
                                   max_queue=max_queue,
                                   max_context=self.max_context)
        if prefill_buckets is None:
            prefill_buckets = _default_buckets(block_size,
                                               self.max_context)
        for b in prefill_buckets:
            if b % block_size:
                raise ValueError(
                    f"prefill bucket {b} is not a multiple of "
                    f"block_size {block_size}")
        self._buckets = sorted(int(b) for b in prefill_buckets)
        # host-side batch state (authoritative; staged per dispatch)
        self._slots: List[Optional[Request]] = [None] * self.max_batch
        self._tables = np.full((self.max_batch, self.max_blocks_per_seq),
                               SCRATCH_BLOCK, dtype=np.int32)
        self._lengths = np.zeros(self.max_batch, dtype=np.int32)
        # device-resident loop state
        self._tokens = jnp.zeros(self.max_batch, dtype=jnp.int32)
        self._done = jnp.zeros(self.max_batch, dtype=bool)
        # compiled steps (ONE jit each; trace cache keyed by shape —
        # decode must stay at exactly one trace, tests pin it)
        self._decode = self._build_decode_step()
        self._prefill = jax.jit(self._run_prefill)
        self._write = jax.jit(
            lambda pool, kv, blocks: self._write_pages(pool, kv, blocks),
            donate_argnums=(0,))
        # NOT donated: the emitted-token array a join rewrites is still
        # referenced by that dispatch's LazyStack streaming views — a
        # donation would invalidate tokens a consumer has yet to read
        self._join = jax.jit(
            lambda tok, done, i, v: (tok.at[i].set(v),
                                     done.at[i].set(False)))
        self._init_observability()

    def _init_observability(self):
        """Per-engine children on the process-wide metrics registry
        (DESIGN-OBSERVABILITY.md): latency/TTFT as fixed-bucket
        histograms, queue depth / occupancy / fragmentation as
        COLLECT-TIME function gauges (zero hot-path cost; weakref so a
        dead engine scrapes as absent, not stale), token/dispatch
        counters on the hot path as plain host adds.  ``LLMServer.
        stats()`` reads these back — the registry is the source of
        truth, the ad-hoc dicts are gone.  Children persist after the
        engine dies (counters/histograms are process-lifetime, like
        any Prometheus client); a churny caller that builds many
        engines reclaims them with :meth:`unregister_metrics`."""
        ordinal = next(_engine_ids)
        self._obs_id = f"e{ordinal}"
        # synthetic-lane base: the process-unique ordinal (not a hash)
        # keys the lane range, so two live engines can never interleave
        # request spans on one track
        self._obs_lane_base = _REQ_LANE_BASE + (ordinal << 16)
        self._obs_labels = {"engine": self._obs_id}
        labels = self._obs_labels
        reg = _obs_metrics.registry()
        self._c_dispatches = reg.counter(
            "serving_dispatches_total",
            "batched decode dispatches", labels=labels)
        self._c_tokens = reg.counter(
            "serving_tokens_total",
            "generated tokens (eos-truncated)", labels=labels)
        self._c_requests = reg.counter(
            "serving_requests_completed_total",
            "finalized requests", labels=labels)
        self._h_latency = reg.histogram(
            "serving_latency_s", "request submit→finish latency",
            labels=labels)
        self._h_ttft = reg.histogram(
            "serving_ttft_s", "request submit→first-token latency",
            labels=labels)
        self._h_queue_time = reg.histogram(
            "serving_queue_time_s", "request submit→admission wait",
            labels=labels)
        wr = weakref.ref(self)

        def _gauge_fn(getter):
            def fn():
                eng = wr()
                return None if eng is None else getter(eng)
            return fn

        reg.gauge("serving_queue_depth", "waiting requests",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e.scheduler.queue_depth))
        reg.gauge("serving_active", "requests in the running batch",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e.active_count))
        reg.gauge("serving_kv_fragmentation",
                  "KV block-pool fragmentation [0,1]",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e._kv.allocator.stats()
                      ["fragmentation"]))
        reg.gauge("serving_done_poll_interval",
                  "dispatches between EOS polls (auto-tuned)",
                  labels=labels).set_function(
            _gauge_fn(lambda e: e.done_poll_interval))
        self._obs_metric_names = (
            "serving_dispatches_total", "serving_tokens_total",
            "serving_requests_completed_total", "serving_latency_s",
            "serving_ttft_s", "serving_queue_time_s",
            "serving_queue_depth", "serving_active",
            "serving_kv_fragmentation", "serving_done_poll_interval")

    def unregister_metrics(self):
        """Reclaim this engine's labeled children from the process-wide
        registry.  Engine-churn hygiene: children are process-lifetime
        by default (Prometheus semantics), so a caller that builds many
        short-lived engines calls this when an engine is retired to
        keep scrape output and registry memory bounded."""
        reg = _obs_metrics.registry()
        for name in self._obs_metric_names:
            reg.unregister(name, labels=self._obs_labels)

    # -- compiled steps ------------------------------------------------------
    def _run_prefill(self, params, ids, length):
        return prefill_forward(params, self._cfg, ids, length)

    @staticmethod
    def _write_pages(pool, kv, blocks):
        from .kv_cache import write_prompt_pages
        return write_prompt_pages(pool, kv, blocks)

    def _build_decode_step(self):
        cfg, eos, pad = self._cfg, self.eos_id, self.pad_id

        def step(params, pool, table, lengths, tokens, done):
            active = (lengths > 0) & jnp.logical_not(done)
            pool, logits = decode_forward(params, cfg, pool, table,
                                          lengths, tokens, active)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            emit = jnp.where(active, nxt, jnp.int32(pad))
            if eos is not None:
                done = done | (active & (nxt == jnp.int32(eos)))
            return pool, emit, done

        return jax.jit(step, donate_argnums=(1,))

    # -- front door ----------------------------------------------------------
    def submit(self, prompt_ids, max_tokens: int,
               stream_cb=None) -> Request:
        """Enqueue a generation request (thread-safe).  Returns the
        :class:`Request`; its ``future`` resolves to a
        :class:`GenerationResult`.  Raises
        :class:`~.scheduler.QueueFull` at queue capacity and
        ``ValueError`` for requests the pool geometry can never run."""
        req = Request(prompt_ids, max_tokens, stream_cb=stream_cb)
        if len(req.prompt) > self._buckets[-1]:
            raise ValueError(
                f"prompt length {len(req.prompt)} exceeds the largest "
                f"prefill bucket {self._buckets[-1]}")
        return self.scheduler.submit(req)

    # -- engine loop ---------------------------------------------------------
    def step(self) -> bool:
        """Admit waiting requests, then run ONE batched decode
        dispatch.  Returns True while there is (or may be) work."""
        self._admit()
        active = [s for s, r in enumerate(self._slots) if r is not None]
        if not active:
            return self.scheduler.queue_depth > 0
        self._grow_pages(active)
        with _obs_trace.span(
                "serving.dispatch",
                args=({"active": len(active)}
                      if _obs_trace.enabled() else None)):
            # async H2D staging of the (tiny) host-authoritative batch
            # layout; the decode dispatch itself never syncs
            table = jax.device_put(self._tables)
            lengths = jax.device_put(self._lengths)
            pool, emit, done = self._decode(self._params, self._kv.pool,
                                            table, lengths, self._tokens,
                                            self._done)
        self._kv.swap_pool(pool)
        self._tokens = emit            # feeds back next dispatch (D2D)
        self._done = done
        self._c_dispatches.inc()
        stack = LazyStack(emit)        # ONE shared fetch, if read
        now = time.monotonic()
        to_finish = []
        for s in active:
            req = self._slots[s]
            req.push_token(
                LazyScalar(stack, post=(lambda a, i=s: a[i])), now)
            if not req.capped:
                self._lengths[s] += 1
            if len(req.lazy_tokens) >= req.max_tokens:
                to_finish.append(s)
        for s in to_finish:
            self._finalize(s)
        if self.eos_id is not None and \
                self._dispatch_count % self.done_poll_interval == 0:
            self._timed_poll()
        return True

    def run_until_idle(self, max_dispatches: int = 100_000):
        """Pump :meth:`step` until queue and batch drain (tests/CLI)."""
        n = 0
        while self.step():
            n += 1
            if n > max_dispatches:
                raise RuntimeError(
                    f"run_until_idle: still busy after {n} dispatches")
        return n

    # -- admission / prefill -------------------------------------------------
    def _admit(self):
        free = [s for s, r in enumerate(self._slots) if r is None]
        if not free:
            return
        for req in self.scheduler.pop_admissible(len(free)):
            self._start_request(free.pop(0), req)

    def _start_request(self, slot: int, req: Request):
        """Prefill the prompt at its bucket, write its pages, and seat
        it in the batch.  The first generated token comes out of the
        prefill program itself (greedy over the last real position)."""
        Lp = len(req.prompt)
        bucket = shape_bucket(Lp, self._buckets)
        ids = np.zeros((1, bucket), dtype=np.int32)
        ids[0, :Lp] = req.prompt
        with _obs_trace.span(
                "serving.prefill",
                args=({"bucket": bucket, "prompt_len": Lp}
                      if _obs_trace.enabled() else None)):
            kv, first_tok, _ = self._prefill(self._params,
                                             jax.device_put(ids),
                                             np.int32(Lp))
        nb_needed = self._kv.blocks_for_tokens(Lp)
        blocks = self._kv.allocator.allocate(nb_needed)
        blocks_arr = np.full(bucket // self.block_size, SCRATCH_BLOCK,
                             dtype=np.int32)
        blocks_arr[:nb_needed] = blocks
        self._kv.swap_pool(self._write(self._kv.pool, kv,
                                       jax.device_put(blocks_arr)))
        req.slot = slot
        req.blocks = blocks
        self._slots[slot] = req
        self._tables[slot, :] = SCRATCH_BLOCK
        self._tables[slot, :nb_needed] = blocks
        self._lengths[slot] = Lp
        self._tokens, self._done = self._join(self._tokens, self._done,
                                              np.int32(slot), first_tok)
        req.push_token(LazyScalar(first_tok), time.monotonic())
        if req.max_tokens == 1:
            self._finalize(slot)

    def _grow_pages(self, active: List[int]):
        """Append-allocate the next block for requests whose upcoming
        write crosses a block boundary.  Reservation-gated admission
        guarantees success within ``req.reserved_blocks``; a slot at
        its budget is a device-done request the host has not polled
        yet — growth (and length advance) stop, its masked writes land
        in scratch."""
        for s in active:
            req = self._slots[s]
            if req.capped:
                continue
            have = len(req.blocks)
            if int(self._lengths[s]) < have * self.block_size:
                continue
            if have >= req.reserved_blocks or \
                    have >= self.max_blocks_per_seq:
                req.capped = True
                continue
            blk = self._kv.allocator.allocate(1)[0]
            req.blocks.append(blk)
            self._tables[s, have] = blk

    # -- completion ----------------------------------------------------------
    def _timed_poll(self):
        """Auto-tune wrapper around the poll site (auto mode only;
        a fixed explicit interval, or a decided one, goes straight to
        the poll).  While calibrating it polls TWICE: the first poll's
        wall time is dominated by draining the queued dispatch chain —
        that is device compute the loop pays either way, not poll
        overhead — so the cost fed to the tuner is the SECOND,
        empty-chain poll (pure sync + [B] fetch; ``_poll_done`` is
        idempotent).  The per-dispatch unit time is the full inter-poll
        wall including the drain.  The shared
        :class:`~paddle_tpu.framework.dispatch.AutoFoldTuner` then
        freezes ``done_poll_interval`` at the smallest cadence whose
        amortized sync overhead is at most ``target`` of the dispatch
        time — a device-bound loop correctly stays near the tight
        cadence instead of saturating at the bound, keeping the
        EOS→reclaim occupancy loss small (DESIGN-SERVING.md §EOS)."""
        tuner = self._poll_tuner
        if tuner is None or tuner.decided:
            self._poll_done()
            return
        t0 = time.monotonic()
        self._poll_done()            # drains the in-flight chain
        t1 = time.monotonic()
        self._poll_done()            # chain empty: pure poll cost
        t2 = time.monotonic()
        n = self._dispatch_count - self._last_poll_dispatches
        if self._last_poll_end is not None and n > 0:
            tuner.observe(1, t2 - t1, (t1 - self._last_poll_end) / n)
        else:
            # first poll: compile/warmup-shaped, tuner discards it
            tuner.observe(1, t2 - t1, t1 - t0)
        self._last_poll_end = t2
        self._last_poll_dispatches = self._dispatch_count
        if tuner.decided:
            self.done_poll_interval = tuner.fold
            d = tuner.decision
            self._poll_decision = {
                "done_poll_interval": self.done_poll_interval,
                "poll_cost_ms": d["host_ms_per_step"],
                "dispatch_ms": d["device_ms_per_step"],
                "target": d["overhead_target"],
                "max": d["max_fold"],
            }

    def _poll_done(self):
        """THE group-boundary sync: fetch the [B] device done-mask so
        EOS'd requests free their slot/pages.  Runs every
        ``done_poll_interval`` dispatches, never inside one."""
        with _obs_trace.span("serving.poll"):
            done = np.asarray(jax.device_get(self._done))
        for s, req in enumerate(self._slots):
            if req is not None and bool(done[s]):
                self._finalize(s)

    def _finalize(self, slot: int):
        """Consumer-boundary materialization: the request is leaving —
        resolving its future IS the read, so the (single, shared per
        dispatch-stack) D2H transfers are sanctioned here."""
        req = self._slots[slot]
        toks = [int(t) for t in req.lazy_tokens]
        if self.eos_id is not None and self.eos_id in toks:
            toks = toks[:toks.index(self.eos_id) + 1]
        req.stats.finished = time.monotonic()
        req.stats.generated = len(toks)
        self.scheduler.finish(req)
        if req.blocks:
            self._kv.allocator.free(req.blocks)
            req.blocks = []
        self._slots[slot] = None
        self._lengths[slot] = 0
        self._tables[slot, :] = SCRATCH_BLOCK
        self._observe_finalize(slot, req, len(toks))
        req.future.set_result(
            GenerationResult(req.id, toks, req.stats))

    def _observe_finalize(self, slot: int, req: Request, n_toks: int):
        """Registry + timeline record of one finished request.  The
        lifecycle spans (queued→prefill→decode-groups→done) are
        reconstructed RETROACTIVELY from the RequestStats milestones —
        same monotonic clock as the live spans — on a synthetic
        per-slot track, so Perfetto shows concurrent requests as
        parallel lanes without the hot loop carrying span objects."""
        st = req.stats
        self._c_requests.inc()
        self._c_tokens.inc(n_toks)
        if st.latency is not None:
            self._h_latency.observe(st.latency)
        if st.ttft is not None:
            self._h_ttft.observe(st.ttft)
        if st.queue_time is not None:
            self._h_queue_time.observe(st.queue_time)
        if not _obs_trace.enabled():
            return
        lane = self._obs_lane_base + slot
        _obs_trace.set_track_name(
            lane, f"serving-{self._obs_id}-slot{slot}")
        args = {"request_id": req.id, "prompt_len": st.prompt_len,
                "generated": st.generated}
        _obs_trace.add_span("request", st.submitted, st.finished,
                            tid=lane, args=args)
        if st.admitted is not None:
            _obs_trace.add_span("request.queued", st.submitted,
                                st.admitted, tid=lane)
            if st.first_token is not None:
                _obs_trace.add_span("request.prefill", st.admitted,
                                    st.first_token, tid=lane)
                _obs_trace.add_span("request.decode-groups",
                                    st.first_token, st.finished,
                                    tid=lane)

    # -- warmup / stats ------------------------------------------------------
    def warmup(self, prompt_lengths: Optional[Sequence[int]] = None
               ) -> Dict[str, float]:
        """Ahead-of-time compile of the serving programs (ROADMAP
        "cold-start as a product metric"): every prefill bucket the
        given prompt lengths touch (default: all buckets), the page
        writer, the join op, and THE decode step.  Returns wall-times;
        call before traffic cuts over — this is the one engine method
        allowed to block on device completion."""
        t0 = time.monotonic()
        buckets = (sorted({shape_bucket(int(n), self._buckets)
                           for n in prompt_lengths})
                   if prompt_lengths else list(self._buckets))
        per_bucket = {}
        for b in buckets:
            tb = time.monotonic()
            ids = np.zeros((1, b), dtype=np.int32)
            kv, tok, _ = self._prefill(self._params,
                                       jax.device_put(ids), np.int32(1))
            blocks_arr = np.full(b // self.block_size, SCRATCH_BLOCK,
                                 dtype=np.int32)
            self._kv.swap_pool(self._write(self._kv.pool, kv,
                                           jax.device_put(blocks_arr)))
            jax.block_until_ready(tok)
            per_bucket[b] = round(time.monotonic() - tb, 4)
        self._tokens, self._done = self._join(
            self._tokens, self._done, np.int32(0), jnp.int32(0))
        td = time.monotonic()
        pool, emit, done = self._decode(
            self._params, self._kv.pool, jax.device_put(self._tables),
            jax.device_put(self._lengths), self._tokens, self._done)
        self._kv.swap_pool(pool)
        self._tokens, self._done = emit, done
        jax.block_until_ready(emit)
        decode_s = time.monotonic() - td
        return {"warmup_s": round(time.monotonic() - t0, 4),
                "decode_compile_s": round(decode_s, 4),
                "prefill_bucket_s": per_bucket,
                "buckets": buckets}

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def _dispatch_count(self) -> int:
        """Dispatch count read back from the registry counter — the ONE
        copy of this state (stats(), poll cadence, tuner deltas).
        Always incremented with host ints, so the host-only read is
        exact and sync-free."""
        return int(self._c_dispatches.collect(materialize=False))

    def compile_stats(self) -> Dict[str, int]:
        """Recompile-pin introspection (mirrors Model.compile_stats):
        ``decode_traces`` MUST stay 1 across any join/leave pattern."""
        def _size(fn):
            try:
                return fn._cache_size()
            except Exception:
                return -1
        return {"decode_traces": _size(self._decode),
                "prefill_traces": _size(self._prefill),
                "write_traces": _size(self._write),
                "join_traces": _size(self._join)}

    def stats(self) -> Dict[str, object]:
        st = {"active": self.active_count,
              "queue_depth": self.scheduler.queue_depth,
              "dispatches": self._dispatch_count,
              "total_tokens": int(
                  self._c_tokens.collect(materialize=False)),
              "done_poll_interval": self.done_poll_interval,
              "kv": self._kv.allocator.stats()}
        if self._poll_decision is not None:
            st["done_poll_decision"] = dict(self._poll_decision)
        st.update(self.compile_stats())
        return st
