"""In-program token sampling: temperature / top-k / top-p INSIDE the
compiled decode step (DESIGN-SERVING.md §Long-context tier).

The zero-recompile contract is the design driver: per-request sampling
parameters ride the decode signature as ``[B]`` *data* vectors
(``temperature``, ``top_k``, ``top_p``, ``seed``) exactly like page
tables and lengths, so a greedy request and a nucleus-sampling request
share one compiled program and membership churn still costs no
retraces.  Randomness uses the PR-5 in-program PRNG pattern
(DESIGN-PERF.md §Step folding): the per-row key derives *inside* the
program as ``fold_in(PRNGKey(seed_b), position_b)`` where ``position``
is the sampled token's sequence index — a pure function of the
request, never of its batch slot, its neighbors, or the dispatch
count.  Consequences, all test-pinned:

- same ``seed`` ⇒ same token sequence, run to run and machine-state
  free;
- join/leave invariance: a request samples the identical sequence
  alone or inside a churning batch (its logits are exact across
  batching already — §Exactness — and its keys never see the batch);
- the sequential oracle (``decode_model.reference_decode``) derives
  the same keys and therefore reproduces sampled output exactly.

``temperature == 0`` rows take the greedy argmax — greedy is the
``temperature=0`` point of the same program, not a separate path.
Sampling itself is Gumbel-max over the filtered, scaled logits:
``argmax(logits/T + G)`` is a categorical draw from
``softmax(logits/T)`` restricted to the kept support, so no
normalization or CDF inversion runs on device.  Top-k keeps the k
largest logits (``k <= 0`` keeps all); top-p keeps the smallest
prefix of the probability-sorted distribution whose cumulative mass
reaches ``p`` (the standard nucleus rule: a token is kept when the
mass *before* it is ``< p``, so the top token always survives and the
boundary token that crosses ``p`` is included).  Both filters mask
with the serving stack's large-finite ``MASK_VALUE`` — never ``-inf``
— for the same NaN-hygiene reasons as the attention masks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .ragged_attention import MASK_VALUE

#: floor for temperature / top-p so the temperature==0 greedy select
#: never divides by zero and top_p==0 degenerates to the top token
_EPS = 1e-6


def sample_tokens(logits, temperature, top_k, top_p, seed, position):
    """``[B, V]`` logits → ``[B]`` int32 token ids, fully in-program.

    ``temperature`` ``[B]`` f32 (0 = greedy); ``top_k`` ``[B]`` int32
    (<= 0 = off); ``top_p`` ``[B]`` f32 (>= 1 = off); ``seed`` ``[B]``
    uint32; ``position`` ``[B]`` int32 — the sequence index of the
    token being sampled (the PRNG counter).
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        lf = logits.astype(jnp.float32)
        scaled = lf / jnp.maximum(temperature, _EPS)[:, None]

        # top-k: kth-largest threshold per row; k<=0 disables
        sorted_desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
        k = jnp.clip(top_k.astype(jnp.int32), 1, V)
        kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None],
                                  axis=1)
        keep = (top_k <= 0)[:, None] | (scaled >= kth)
        filtered = jnp.where(keep, scaled, MASK_VALUE)

        # top-p over the post-top-k distribution
        probs = jax.nn.softmax(filtered, axis=-1)
        p_desc = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
        csum = jnp.cumsum(p_desc, axis=-1)
        p = jnp.clip(top_p, _EPS, 1.0)[:, None]
        in_nucleus = (csum - p_desc) < p       # mass BEFORE token < p
        cutoff = jnp.min(jnp.where(in_nucleus, p_desc, jnp.inf),
                         axis=-1, keepdims=True)
        keep_p = (top_p >= 1.0)[:, None] | (probs >= cutoff)
        filtered = jnp.where(keep_p, filtered, MASK_VALUE)

        def _row_gumbel(s, pos):
            key = jax.random.fold_in(jax.random.PRNGKey(s), pos)
            return jax.random.gumbel(key, (V,), dtype=jnp.float32)

        g = jax.vmap(_row_gumbel)(seed.astype(jnp.uint32),
                                  position.astype(jnp.int32))
        sampled = jnp.argmax(filtered + g, axis=-1).astype(jnp.int32)
        return jnp.where(temperature > 0.0, sampled, greedy)

    # all-greedy batches (the common serving default) skip the sort /
    # cumsum / Gumbel work at RUNTIME — lax.cond is data-dependent,
    # so the one compiled program still serves any greedy/sampled mix
    return jax.lax.cond(jnp.any(temperature > 0.0), _sampled,
                        lambda _: greedy, None)


def sample_tokens_grid(logits, temperature, top_k, top_p, seed,
                       positions):
    """``[B, S, V]`` logits → ``[B, S]`` int32 tokens: the window
    variant for speculative verify (DESIGN-SERVING.md §Speculative
    tier).

    Per-request sampling vectors stay ``[B]``; ``positions`` is
    ``[B, S]`` — each window slot's sequence index.  Flattens the
    window into the batch axis and reuses :func:`sample_tokens`
    verbatim, so slot ``(b, i)`` draws with the exact key
    ``fold_in(PRNGKey(seed_b), positions_{b,i})`` the sequential
    single-token path would use at that index — the property the
    speculative accept rule's exactness rests on.
    """
    B, S, V = logits.shape
    flat = sample_tokens(logits.reshape(B * S, V),
                         jnp.repeat(temperature, S),
                         jnp.repeat(top_k, S),
                         jnp.repeat(top_p, S),
                         jnp.repeat(seed, S),
                         positions.reshape(B * S))
    return flat.reshape(B, S)
