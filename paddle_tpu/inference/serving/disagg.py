"""Disaggregated prefill/decode serving: two phase-pinned pools, one
front door (DESIGN-SERVING.md §Disaggregated tier).

Chunked prefill (PR 14) got a running decode's p99 inter-token gap
from 1281 ms to 88 ms past a 32k admission by slicing prompt work
between decode dispatches; the residual jitter is exactly the chunks
still sharing the decode replica's dispatch queue.  Disaggregation
removes the sharing: prefill-role replicas own admission and chunked
prefill, decode-role replicas own the steady-state batch, and a
finished prompt crosses between them as a :class:`~.migration.
PageMigration` — KV pages plus sampling state, token-exact by
construction (sampling keys are pure ``(seed, position)`` functions).

:class:`DisaggRouter` composes two :class:`~.router.ServingRouter`
pools and owns the transition between them:

- **submit** routes to the prefill pool and returns an OUTER future;
  the engine-side future is tracked so the router can re-admit.
- **handoff** is the first-class transition: each prefill replica's
  pump hands finished-prompt tickets to :meth:`_handoff`, which
  places them on the least-loaded decode replica; a full decode pool
  parks the ticket for the retry loop (next-least-loaded was already
  tried — ``ServingRouter.submit_migration`` walks the pool).
- **failover**: a prefill replica that dies mid-prompt fails its
  engine futures; the tracker sees an un-handed-off failure and
  re-admits the prompt from scratch (seeds are resolved at the OUTER
  door, so a re-admitted sampled request still matches the oracle).
  A decode pool with no room sheds into the pending queue, never at
  the client.
- **scaling** stays per-pool and per-signal: the prefill router
  scales on admission queue depth, the decode router on windowed
  inter-token p99 (``phase="decode"`` selects the signal) — the two
  pools breathe independently, which is the entire point of the
  architecture (PAPERS.md arxiv 2605.25645).

Multi-host: see DESIGN-SERVING.md — the ticket rides the fleet KV
registry (publish under the request's chain hash, importer fetches
and scatters) through the exact same export/import seam used here
in-process.
"""

from __future__ import annotations

import itertools
import threading
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

from ...observability import events as _obs_events
from .migration import PageMigration
from .router import Overloaded, ServingRouter

__all__ = ["DisaggRouter"]


class _Tracked:
    """One client request's crossing state: the outer future the
    client holds, the submission args needed to re-admit it, and
    whether its ticket ever reached the decode pool."""

    __slots__ = ("outer", "prompt", "kwargs", "handed_off", "retries")

    def __init__(self, outer: Future, prompt, kwargs: Dict[str, Any]):
        self.outer = outer
        self.prompt = list(prompt)
        self.kwargs = kwargs
        self.handed_off = False
        self.retries = 0


class DisaggRouter:
    """Phase-disaggregated serving front door over two replica pools.

    ``prefill_factory`` / ``decode_factory`` are zero-arg callables
    returning RUNNING ``LLMServer`` instances with ``role="prefill"``
    and ``role="decode"`` respectively (any other role is refused at
    spawn — the :class:`~.router.ServingRouter` phase contract).
    ``prefill_pool`` / ``decode_pool`` dicts forward to the two
    routers (``min_replicas``, ``slo_p99_s``, …); ``phase`` is set by
    this class and refused if passed.  ``retry_interval_s=0``
    disables the background retry/control thread — tests drive
    :meth:`control_round` directly.
    """

    def __init__(self, prefill_factory: Callable[[], Any],
                 decode_factory: Callable[[], Any], *,
                 prefill_pool: Optional[Dict[str, Any]] = None,
                 decode_pool: Optional[Dict[str, Any]] = None,
                 retry_interval_s: float = 0.02,
                 max_readmissions: int = 3):
        prefill_pool = dict(prefill_pool or {})
        decode_pool = dict(decode_pool or {})
        for pool, name in ((prefill_pool, "prefill_pool"),
                           (decode_pool, "decode_pool")):
            if "phase" in pool:
                raise ValueError(
                    f"{name}['phase'] is owned by DisaggRouter")
        self.max_readmissions = int(max_readmissions)
        self._lock = threading.Lock()
        self._by_future: Dict[int, _Tracked] = {}
        self._pending: List[PageMigration] = []
        # seeds resolve at THIS door: the engine's per-request default
        # (request id) would change on re-admission, silently changing
        # a sampled request's output across a failover — a counter
        # fixed into the tracked kwargs keeps re-admitted output
        # identical while unseeded requests still differ pairwise
        self._auto_seed = itertools.count(0x5EED)
        self._closed = False

        def build_prefill():
            server = prefill_factory()
            hook = getattr(server, "set_handoff_handler", None)
            if hook is not None:
                hook(self._handoff)
            return server

        # decode pool first: a prefill replica can finish a prompt
        # (and call _handoff) the moment its pump starts
        self.decode = ServingRouter(decode_factory, phase="decode",
                                    **decode_pool)
        try:
            self.prefill = ServingRouter(build_prefill,
                                         phase="prefill",
                                         **prefill_pool)
        except Exception:
            self.decode.close()
            raise
        self.retry_interval_s = float(retry_interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.retry_interval_s > 0:
            self._thread = threading.Thread(
                target=self._retry_loop,
                name="paddle-tpu-disagg-router", daemon=True)
            self._thread.start()

    # -- front door --------------------------------------------------------
    def submit(self, prompt_ids, max_tokens: int, stream_cb=None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed=None) -> Future:
        """Route one request through the disaggregated pipeline;
        returns a future resolving to the usual
        :class:`~.engine.GenerationResult`.  Raises
        :class:`~.router.Overloaded` when the prefill pool sheds."""
        if self._closed:
            raise RuntimeError("router closed")
        if seed is None and temperature > 0.0:
            seed = next(self._auto_seed)
        kwargs = {"max_tokens": max_tokens, "stream_cb": stream_cb,
                  "temperature": temperature, "top_k": top_k,
                  "top_p": top_p, "seed": seed}
        entry = _Tracked(Future(), prompt_ids, kwargs)
        self._admit(entry)
        return entry.outer

    def _admit(self, entry: _Tracked):
        inner = self.prefill.submit(entry.prompt, **entry.kwargs)
        with self._lock:
            self._by_future[id(inner)] = entry
        inner.add_done_callback(self._on_inner_done)

    def _on_inner_done(self, inner: Future):
        with self._lock:
            entry = self._by_future.pop(id(inner), None)
        if entry is None:
            return
        exc = inner.exception()
        if exc is None:
            entry.outer.set_result(inner.result())
            return
        # prefill-death failover: an engine-side failure BEFORE the
        # handoff means the pages died with the replica — the prompt
        # is all we need, re-admit it (the decode pool never saw it,
        # so there is no duplicate to race)
        if (self._closed or entry.handed_off
                or entry.retries >= self.max_readmissions):
            entry.outer.set_exception(exc)
            return
        entry.retries += 1
        _obs_events.record("prompt_readmitted",
                           retries=entry.retries,
                           error=f"{type(exc).__name__}")
        try:
            self._admit(entry)
        except Exception as e:  # noqa: BLE001 — re-admission door
            # shut too: the client gets the truth, not a hang
            entry.outer.set_exception(e)

    # -- the prefill→decode transition -------------------------------------
    def _handoff(self, mig: PageMigration):
        """Runs on a prefill replica's pump thread for every staged
        ticket.  Marks the crossing BEFORE placement: once the ticket
        exists, re-admitting the prompt would double-generate — from
        here on, failures surface on the future, never via retry."""
        with self._lock:
            entry = self._by_future.get(id(mig.request.future))
        if entry is not None:
            entry.handed_off = True
        try:
            self.decode.submit_migration(mig)
        except Overloaded:
            # every decode replica full: park and retry — admission
            # pressure must never fail a prompt that already paid for
            # its prefill
            with self._lock:
                self._pending.append(mig)

    def pump_pending(self) -> int:
        """Retry parked tickets against the decode pool (retry
        thread; tests call it directly).  Returns how many placed."""
        with self._lock:
            pend, self._pending = self._pending, []
        placed = 0
        for mig in pend:
            if mig.consumed:
                continue
            try:
                self.decode.submit_migration(mig)
                placed += 1
            except Overloaded:
                with self._lock:
                    self._pending.append(mig)
            except Exception as e:  # noqa: BLE001 — geometry/consumed
                # refusals are terminal for this ticket
                if not mig.request.future.done():
                    mig.request.future.set_exception(e)
        return placed

    @property
    def pending_handoffs(self) -> int:
        with self._lock:
            return len(self._pending)

    def _retry_loop(self):
        while not self._stop.wait(self.retry_interval_s):
            try:
                self.pump_pending()
            except Exception as e:  # noqa: BLE001
                _obs_events.record("handoff_retry_failed",
                                   error=f"{type(e).__name__}: {e}")

    # -- control / observability -------------------------------------------
    def control_round(self) -> Dict[str, Any]:
        """One decision round over BOTH pools plus a pending-ticket
        pump (each pool also runs its own background loop when its
        ``decision_interval_s > 0``)."""
        return {"prefill": self.prefill.control_round(),
                "decode": self.decode.control_round(),
                "handoffs_placed": self.pump_pending()}

    def stats(self) -> Dict[str, Any]:
        return {
            "prefill_replicas": self.prefill.num_replicas,
            "decode_replicas": self.decode.num_replicas,
            "prefill_shedding": self.prefill.shedding,
            "decode_shedding": self.decode.shedding,
            "prefill_p99_s": self.prefill.windowed_p99_s(),
            "decode_intertoken_p99_s": self.decode.windowed_p99_s(),
            "pending_handoffs": self.pending_handoffs,
            "tracked_in_flight": len(self._by_future),
        }

    def to_config(self) -> Dict[str, Any]:
        """Both pools' knob profiles (see
        :meth:`~.router.ServingRouter.to_config`)."""
        return {"prefill_pool": self.prefill.to_config(),
                "decode_pool": self.decode.to_config(),
                "retry_interval_s": self.retry_interval_s,
                "max_readmissions": self.max_readmissions}

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        """Close both pools (prefill first — no new tickets can be
        cut while the decode pool still drains) and fail anything
        still parked."""
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.prefill.close()
        self.decode.close()
        with self._lock:
            pend, self._pending = self._pending, []
        exc = RuntimeError("router closed before completion")
        for mig in pend:
            if not mig.request.future.done():
                mig.request.future.set_exception(exc)

    def __enter__(self) -> "DisaggRouter":
        return self

    def __exit__(self, *a):
        self.close()
        return False
