"""paddle_tpu.inference.serving — continuous-batching decode server.

The "millions of users" path (ROADMAP): a persistent compiled decode
loop over a paged KV cache with ragged batched attention, continuous
batching with block-budget admission control, and a lazy-streaming
front door.  See DESIGN-SERVING.md for the architecture and the
what-recompiles/what-never-does contract.

    from paddle_tpu.inference.serving import LLMServer
    server = LLMServer(gpt_network, max_batch=8, num_blocks=512)
    future = server.submit(prompt_ids, max_tokens=64)
    print(future.result().tokens)
"""

from .kv_cache import (BlockAllocator, OutOfBlocks, PagedKVCache,
                       SCRATCH_BLOCK, gather_pages, paged_append,
                       write_prompt_pages, write_prompt_pages_group)
from .ragged_attention import (causal_prefill_attention,
                               chunked_prefill_attention,
                               paged_decode_attention,
                               ragged_decode_attention,
                               resolve_paged_attention_mode)
from .sampling import sample_tokens, sample_tokens_grid
from .prefix_cache import PrefixCache, PrefixEntry
from .decode_model import (ServingModelConfig, chunk_prefill_forward,
                           decode_forward, extract_decode_params,
                           prefill_forward, prefill_group_forward,
                           reference_decode, spec_score_forward)
from .scheduler import QueueFull, Request, RequestStats, Scheduler
from .migration import (MigrationError, PageMigration,
                        gather_request_pages, scatter_request_pages)
from .spec_decode import SPEC_SENTINEL, spec_decode_step
from .engine import DecodeEngine, ENGINE_ROLES, GenerationResult
from .api import LLMServer, filter_spec_stream
from .router import Overloaded, ROUTER_PHASES, ServingRouter
from .disagg import DisaggRouter

__all__ = [
    "BlockAllocator", "OutOfBlocks", "PagedKVCache", "SCRATCH_BLOCK",
    "gather_pages", "paged_append", "write_prompt_pages",
    "write_prompt_pages_group",
    "causal_prefill_attention", "chunked_prefill_attention",
    "paged_decode_attention", "ragged_decode_attention",
    "resolve_paged_attention_mode", "sample_tokens",
    "PrefixCache", "PrefixEntry",
    "ServingModelConfig", "chunk_prefill_forward", "decode_forward",
    "extract_decode_params", "prefill_forward",
    "prefill_group_forward", "reference_decode",
    "QueueFull", "Request", "RequestStats", "Scheduler",
    "MigrationError", "PageMigration", "gather_request_pages",
    "scatter_request_pages",
    "SPEC_SENTINEL", "spec_decode_step", "sample_tokens_grid",
    "spec_score_forward", "filter_spec_stream",
    "DecodeEngine", "ENGINE_ROLES", "GenerationResult", "LLMServer",
    "Overloaded", "ROUTER_PHASES", "ServingRouter", "DisaggRouter",
]
