"""Fused ragged paged-attention Pallas kernel (arxiv 2604.15464).

The gather+mask decode composition (``kv_cache.gather_pages`` →
``ragged_attention.ragged_decode_attention``) materializes
``[B, MAXNB*BS, H, Dh]`` K and V per layer — the page-table gather
padded to the table's maximum extent.  At 1k context that is noise;
at 32k context it is the whole memory story: every decode dispatch
writes two full-context-sized intermediates per layer that the
attention reduction immediately consumes.

This kernel is the long-context answer: ONE ``pallas_call`` walks
each request's page table block by block and accumulates the
attention output with an online (flash-style) softmax.  The working
set per request is a single ``[BS, H, Dh]`` KV block plus ``[H]``-row
running statistics — independent of context length — and the walk's
trip count is the request's REAL block count (``ceil(len/BS)``), so
a short request in a long-context batch does proportional work: the
ragged part of Ragged Paged Attention.

Structure: requests unroll statically over the (small) ``max_batch``
axis; each request runs a ``fori_loop`` over its blocks whose body
dynamically indexes the layer's K/V pools (``ref[pl.ds(block_id,
1)]``) — the page table is *data* read inside the kernel, exactly the
zero-recompile contract the engine pins.  The last block's tail and
empty rows mask with the serving stack's usual exact-zero arithmetic
(``MASK_VALUE`` / ``DENOM_TINY`` — an empty slot returns exact 0.0,
never NaN).

On this CPU container the kernel runs in **interpret mode**
(``pl.pallas_call(interpret=True)``): Pallas lowers the same body
through the interpreter into the XLA program, so the fused structure
(no full-extent gather) is exercised end to end without TPU hardware.
Two real-TPU evolutions are deliberately left to the live-TPU
backlog (ROADMAP): lane-aligning ``[BS, H*Dh]`` tiles to the 128-lane
grid, and moving the block walk onto a
``PrefetchScalarGridSpec`` grid whose index_map reads the page table
(the canonical Mosaic pipelining shape — this jaxlib's *interpreter*
cannot run grid machinery under the repo's global ``jax_enable_x64``,
which is why the in-body walk is the portable form here).

Numerics: statistics in f32 like the reference; the online softmax
re-associates the reduction, so outputs match the gather composition
to reduction-order tolerance (the kernel-vs-reference pin in
``tests/test_serving_longcontext.py`` holds 2e-6, the same bound the
gather path documents vs the sequential oracle).  Selection lives
behind ``ragged_attention.paged_decode_attention``
(DESIGN-SERVING.md §Long-context tier).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .ragged_attention import DENOM_TINY, MASK_VALUE


def _paged_attn_kernel(block_size: int, scale: float,
                       table_ref, len_ref, q_ref,   # inputs
                       k_ref, v_ref,                # per-layer pools
                       o_ref):                      # [B, H, Dh] out
    B = q_ref.shape[0]
    bs = jnp.int32(block_size)
    for b in range(B):                 # static unroll: B = max_batch
        length = len_ref[b]
        nb = jax.lax.div(length + bs - jnp.int32(1), bs)
        qf = q_ref[b].astype(jnp.float32)            # [H, Dh]

        def body(j, carry, b=b, qf=qf, length=length):
            m, l, acc = carry
            blk = table_ref[b, j]
            k = k_ref[pl.ds(blk, 1)][0].astype(jnp.float32)
            v = v_ref[pl.ds(blk, 1)][0].astype(jnp.float32)
            logits = jnp.einsum(
                "hd,thd->ht", qf, k,
                preferred_element_type=jnp.float32) * scale
            pos = j * bs + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_size), 1)[0]
            valid = pos < length                     # [BS]
            logits = jnp.where(valid[None, :], logits, MASK_VALUE)
            m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(logits - m_new[:, None])
            p = jnp.where(valid[None, :], p, 0.0)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[:, None] + jnp.einsum(
                "ht,thd->hd", p, v,
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        H, Dh = qf.shape
        init = (jnp.full((H,), MASK_VALUE, jnp.float32),
                jnp.zeros((H,), jnp.float32),
                jnp.zeros((H, Dh), jnp.float32))
        m, l, acc = jax.lax.fori_loop(jnp.int32(0), nb, body, init)
        denom = jnp.maximum(l, DENOM_TINY)[:, None]
        o_ref[b] = (acc / denom).astype(o_ref.dtype)


def paged_ragged_attention(pool_k, pool_v, page_table, lengths, q,
                           *, interpret: bool, scale=None):
    """Fused paged decode attention — no full-extent gather.

    ``pool_k``/``pool_v`` ``[NB, BS, H, Dh]`` (one layer's K/V pool);
    ``page_table`` ``[B, MAXNB]`` int32; ``lengths`` ``[B]`` int32;
    ``q`` ``[B, H, Dh]``.  Returns ``[B, H, Dh]`` in ``q``'s dtype.
    Call through :func:`ragged_attention.paged_decode_attention` —
    that seam owns backend/env selection.
    """
    NB, BS, H, Dh = pool_k.shape
    B, MAXNB = page_table.shape
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)
    kernel = functools.partial(_paged_attn_kernel, BS, float(scale))
    fn = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=bool(interpret))
    return fn(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
              q, pool_k, pool_v)


def attention_working_set_bytes(num_batch: int, max_blocks: int,
                                block_size: int, num_heads: int,
                                head_dim: int, itemsize: int = 4
                                ) -> dict:
    """Analytic per-layer attention working set: the gather
    composition's ``[B, MAXNB*BS, H, Dh]`` K+V intermediates vs the
    kernel's one-block-per-request residency.  Recorded by
    ``bench.py --longcontext`` as the memory story of the tier."""
    per_tok = num_heads * head_dim * itemsize
    gather = 2 * num_batch * max_blocks * block_size * per_tok
    kernel = 2 * num_batch * block_size * per_tok
    return {"gather_bytes": int(gather), "kernel_bytes": int(kernel),
            "ratio": round(gather / max(kernel, 1), 1)}
