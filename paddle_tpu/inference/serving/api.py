"""LLMServer — the serving front door.

    server = LLMServer(net, max_batch=8, block_size=16,
                       num_blocks=512, eos_id=eos, auto_start=False)
    server.warmup([16, 64])          # AOT compile before traffic
    server.start()
    fut = server.submit(prompt_ids, max_tokens=64,
                        stream_cb=on_token)
    result = fut.result()            # GenerationResult

One daemon pump thread owns the engine: it admits, prefers, and
dispatches; ``submit`` only touches the (locked) admission queue and
wakes the pump, so the front door is safe from any thread and never
blocks on device work.  Streaming callbacks receive ``LazyScalar``
token views — reading/formatting one is the CONSUMER's device sync;
an unread stream costs the server nothing (framework/lazy.py).

Speculative multi-token stream-out (DESIGN-SERVING.md §Speculative
tier): an engine built with a draft artifact pushes a fixed ``k+1``
lazy views per decode dispatch — the host cannot know the accepted
count without a sync, so rejected window positions materialize as the
negative :data:`SPEC_SENTINEL` and up to ``k`` bonus tokens past
``max_tokens`` may stream before the device-side stop is polled (the
resolved ``GenerationResult`` is always sentinel-free and clipped).
Consumers that want plain in-order tokens wrap their callback in
:func:`filter_spec_stream`; consumers that already read lazily just
skip negative values.

Backpressure: the admission queue is bounded; ``submit`` raises
:class:`~.scheduler.QueueFull` at capacity.  Stats: ``stats()``
reports queue depth, batch occupancy, KV-pool fragmentation, compile
trace counts, and latency/TTFT percentiles — all read back from the
engine's children on the process-wide metrics registry
(``paddle_tpu.observability``), so ``scrape()`` and this adapter see
the same numbers.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

from ...framework import compile_cache
from .engine import DecodeEngine
from .scheduler import QueueFull  # noqa: F401  (re-export: caller API)
from .spec_decode import SPEC_SENTINEL  # noqa: F401  (re-export)


def filter_spec_stream(cb, max_tokens: Optional[int] = None):
    """Adapt a plain ``cb(request_id, index, int_token)`` callback to
    a speculative engine's stream: drops :data:`SPEC_SENTINEL`
    placeholders, re-numbers the surviving tokens densely, and (when
    ``max_tokens`` is given) suppresses the final window's overshoot
    past the cap.  Reading the lazy view to decide IS a device sync —
    the consumer's sanctioned one (an adapted callback is a consumer
    that reads every token).  Callers who need the zero-sync stream
    keep the raw callback and skip negatives at their own read point.
    """
    counts: Dict[object, int] = {}

    def wrapped(request_id, index, lazy_tok):
        tok = int(lazy_tok)
        if tok == SPEC_SENTINEL:
            return
        n = counts.get(request_id, 0)
        if max_tokens is not None and n >= max_tokens:
            return
        counts[request_id] = n + 1
        cb(request_id, n, tok)

    return wrapped


class LLMServer:
    """Continuous-batching generation server over a trained network.

    ``network``: a ``GPTForCausalLM`` (weights are snapshot at
    construction via ``extract_decode_params``; call
    :meth:`refresh_weights` after further training).  Remaining kwargs
    go to :class:`DecodeEngine`.
    """

    def __init__(self, network=None, *, auto_start: bool = True,
                 idle_wait_s: float = 0.005,
                 metrics_port: Optional[int] = None,
                 on_handoff=None, **engine_kwargs):
        # persistent XLA compilation cache (opt-in via env): restarts
        # of this server skip recompiling the decode/prefill programs
        compile_cache.enable_from_env()
        self.engine = DecodeEngine(network, **engine_kwargs)
        # prefill→decode handoff plane (DESIGN-SERVING.md
        # §Disaggregated tier): a prefill-role engine stages finished
        # prompts out as PageMigration tickets; the pump hands each to
        # ``on_handoff(mig)`` (the router's transition hook) or parks
        # it for :meth:`pop_handoffs`.  A handler that raises fails
        # THAT request's future — never the pump.
        self._on_handoff = on_handoff
        self._handoffs: list = []
        self._idle_wait_s = float(idle_wait_s)
        self._cond = threading.Condition()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._warmup_record: Optional[Dict] = None
        # serving deployments arm the HTTP scrape plane in one arg
        # (DESIGN-OBSERVABILITY.md §Distributed plane): /metrics,
        # /metrics.json, /trace, /healthz over the process-wide
        # registry this engine already records into.  0 = ephemeral
        # port (read it back via `metrics_port`); None = off.
        self._metrics_server = None
        if metrics_port is not None:
            from ...observability import http as _obs_http
            self._metrics_server = _obs_http.serve(int(metrics_port))
        if auto_start:
            self.start()

    @property
    def metrics_port(self) -> Optional[int]:
        """The bound scrape port (None when not armed)."""
        return (None if self._metrics_server is None
                else self._metrics_server.port)

    def set_handoff_handler(self, fn) -> "LLMServer":
        """Install/replace the prefill→decode transition hook (the
        DisaggRouter wires itself in here after the factory builds the
        server).  The pump reads it per round, so installing on a
        running server is safe."""
        self._on_handoff = fn
        return self

    # -- lifecycle -----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "LLMServer":
        if self.running:
            return self
        self._closed = False
        self._thread = threading.Thread(target=self._pump,
                                        name="paddle-tpu-llm-server",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self, unregister_metrics: bool = False):
        """Stop the pump.  In-flight and queued requests get their
        futures failed with RuntimeError — the caller's retry tier
        decides what survives a server teardown, not the server.

        The engine's registry children survive close by default
        (Prometheus semantics: a post-mortem scrape still answers);
        a churny caller that builds many short-lived servers passes
        ``unregister_metrics=True`` to reclaim them."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._fail_all(RuntimeError("server closed before completion"))
        if self._metrics_server is not None:
            # the endpoint dies with the server: a scraper must see
            # connection-refused (target down), never a frozen scrape
            self._metrics_server.close()
            self._metrics_server = None
        if unregister_metrics:
            self.engine.unregister_metrics()

    def __enter__(self) -> "LLMServer":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _pump(self):
        while True:
            with self._cond:
                if self._closed:
                    return
            try:
                busy = self.engine.step()
                busy = self._dispatch_handoffs() or busy
            except Exception as e:   # noqa: BLE001 — a dead pump must
                # not strand callers on futures that never resolve
                self._fail_all(RuntimeError(
                    f"serving engine failed: {type(e).__name__}: {e}"))
                raise
            if not busy:
                with self._cond:
                    if self._closed:
                        return
                    self._cond.wait(self._idle_wait_s)

    def _dispatch_handoffs(self) -> bool:
        """Hand staged migrations to the transition hook (pump
        thread).  Without a hook they park for :meth:`pop_handoffs` —
        a direct-drive caller's polling surface."""
        migs = self.engine.pop_ready_migrations()
        if not migs:
            return False
        for mig in migs:
            if self._on_handoff is None:
                self._handoffs.append(mig)
                continue
            try:
                self._on_handoff(mig)
            except Exception as e:  # noqa: BLE001 — one bad handoff
                # must not take the pump (and every other request) down
                if not mig.request.future.done():
                    mig.request.future.set_exception(RuntimeError(
                        f"prefill→decode handoff failed: "
                        f"{type(e).__name__}: {e}"))
        return True

    def pop_handoffs(self) -> list:
        """Drain migrations parked because no ``on_handoff`` hook was
        installed (thread-safe enough: the pump only appends; callers
        poll)."""
        out, self._handoffs = self._handoffs, []
        return out

    def _fail_all(self, exc: Exception):
        eng = self.engine
        eng._prefill_jobs.clear()      # mid-prefill work dies with us
        for mig in eng.drain_all_migrations() + self.pop_handoffs():
            if not mig.request.future.done():
                mig.request.future.set_exception(exc)
        for s, req in enumerate(eng._slots):
            if req is None:
                continue
            # release pool state BEFORE failing the future: leaked
            # reservations would shrink capacity forever on restart
            eng.scheduler.finish(req)
            if req.blocks:
                eng._kv.allocator.free(req.blocks)
                req.blocks = []
            if req.prefix_entries:
                eng._prefix.release(req.prefix_entries)
                req.prefix_entries = []
            eng._lengths[s] = 0
            eng._slots[s] = None
            if eng.spec_k:
                # speculative lengths live ON DEVICE: a stale positive
                # value would run the dead lane as active on restart
                eng._maxt[s] = 0
                with eng._on_device():
                    eng._spec_clear(s)
            if not req.future.done():
                req.future.set_exception(exc)
        for req in eng.scheduler.drain_waiting():
            if not req.future.done():
                req.future.set_exception(exc)

    # -- traffic -------------------------------------------------------------
    def submit(self, prompt_ids, max_tokens: int, stream_cb=None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed=None):
        """Enqueue a request; returns its ``concurrent.futures.Future``
        resolving to a :class:`~.engine.GenerationResult`.
        ``temperature``/``top_k``/``top_p``/``seed`` select in-program
        sampling (temperature 0 = greedy; a fixed seed makes the
        sampled sequence deterministic — DESIGN-SERVING.md
        §Long-context tier).  Raises :class:`QueueFull` under
        backpressure."""
        req = self.engine.submit(prompt_ids, max_tokens,
                                 stream_cb=stream_cb,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p, seed=seed)
        with self._cond:
            self._cond.notify_all()
        return req.future

    @property
    def role(self) -> str:
        """This server's phase role ("both"/"prefill"/"decode") —
        the router's spawn-time contract check reads it."""
        return self.engine.role

    def submit_migration(self, mig) -> None:
        """Admit a migrated request into this (decode-phase) server's
        engine and wake the pump.  Propagates the engine's refusals
        (:class:`QueueFull` → failover, ``MigrationError``/
        ``ValueError`` → misrouted)."""
        self.engine.submit_migration(mig)
        with self._cond:
            self._cond.notify_all()

    def warmup(self, prompt_lengths: Optional[Sequence[int]] = None):
        """AOT-compile the serving programs BEFORE traffic (must be
        called with the pump stopped — construct with
        ``auto_start=False``).  Returns and records the wall-time
        breakdown; ``stats()`` re-surfaces it so cold-start cost is a
        first-class product metric."""
        if self.running:
            raise RuntimeError(
                "warmup() needs exclusive engine access: construct "
                "LLMServer(auto_start=False), warmup(), then start()")
        self._warmup_record = self.engine.warmup(prompt_lengths)
        return self._warmup_record

    def refresh_weights(self, network, draft=None):
        """Re-snapshot weights from a (re)trained network.  Pump must
        be stopped (same exclusivity contract as warmup).  A
        speculative server passes the refreshed ``draft`` network too;
        refreshing the target alone is allowed (the draft is an
        approximation — a stale one only lowers the accept rate, never
        correctness)."""
        if self.running:
            raise RuntimeError("stop the server before refreshing "
                               "weights")
        from .decode_model import extract_decode_params
        self.engine._params = extract_decode_params(network)
        if draft is not None:
            if not self.engine.spec_k:
                raise ValueError("draft weights on a non-speculative "
                                 "server — construct with draft= first")
            self.engine._draft_params = extract_decode_params(draft)

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """Serving stats, read back FROM the process-wide metrics
        registry (DESIGN-OBSERVABILITY.md): the engine records
        latency/TTFT into its per-engine histogram children and this
        adapter keeps the public dict shape — percentiles are
        histogram-quantile estimates (interpolated within the landing
        bucket) instead of an exact private ring, and the same numbers
        are visible to ``paddle_tpu.observability.scrape()`` and the
        Prometheus dump."""
        eng = self.engine
        st = dict(eng.stats())
        st["completed"] = int(eng._h_latency.collect()["count"])
        st["latency_p50_s"] = round(eng._h_latency.quantile(0.50), 6)
        st["latency_p99_s"] = round(eng._h_latency.quantile(0.99), 6)
        st["ttft_p50_s"] = round(eng._h_ttft.quantile(0.50), 6)
        st["ttft_p99_s"] = round(eng._h_ttft.quantile(0.99), 6)
        if self._warmup_record is not None:
            st["warmup"] = self._warmup_record
        st["compilation_cache_dir"] = compile_cache.active_cache_dir()
        return st
