"""SLO-aware serving router: replica autoscaling + admission shedding
(DESIGN-OBSERVABILITY.md §Action loop, DESIGN-SERVING.md §Router).

:class:`LLMServer` is one engine on one device pool; production
traffic is judged on SLOs under load spikes (PAPERS.md arxiv
2605.25645), which needs the signals the engine already exports —
queue depth, latency histograms, KV fragmentation, all on the
process-wide metrics registry — to *drive* capacity and admission,
not just report them.  :class:`ServingRouter` closes that loop:

- **Routing.**  ``submit`` goes to the least-loaded live replica
  (queue depth + running batch); a replica answering
  :class:`~.scheduler.QueueFull` fails over to the next.  Every
  replica is an ordinary ``LLMServer`` built by the caller's
  ``replica_factory`` — the router never reaches into engine
  internals to admit work.
- **Scaling.**  A background control loop samples the registry
  signals every ``decision_interval_s`` and applies hysteresis: the
  overload signal (queue depth per replica above
  ``scale_up_queue_depth``, or windowed p99 above ``slo_p99_s``)
  must hold for ``windows_up`` consecutive decisions before a spawn,
  the underload signal for ``windows_down`` before a retire, and
  every scale action starts a ``cooldown_s`` lockout — load flapping
  must not flap capacity.  Retiring drains: the victim stops taking
  admissions, finishes its running batch, then closes (its registry
  children are reclaimed — replica churn is by design here).
- **Shedding.**  When overloaded *and* capacity can't grow (at
  ``max_replicas`` or mid-cooldown), the router turns admission
  shedding on: ``submit`` raises :class:`Overloaded` at the door so
  the upstream load balancer sees backpressure immediately instead
  of a latency cliff.  Shedding is a *state* toggled by the control
  loop (events on the transitions), shed volume is a counter, and
  each shed consults the droppable ``router.shed`` fault site so
  chaos plans can suppress relief and test the cliff.
- **p99 over a window.**  Registry histograms are cumulative
  (process-lifetime); an SLO verdict needs *recent* latency.  The
  loop diffs consecutive histogram snapshots and estimates the p99
  of just the completions inside the window — the number
  ``router_p99_s`` exports and the burst chaos test pins.

Every decision lands on the registry (``serving_replicas``,
``router_scale_ups_total``/``router_scale_downs_total``,
``router_shed_total``) and on the decision ring
(``observability.events`` → ``/events``, merged fleet-wide into the
launch controller's ``/fleet/events``).

The control loop reads ONLY host state (queue depths, host-float
histograms) with ``materialize=False`` — it can never add a device
sync to the decode hot path it supervises.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ...distributed.resilience import faults as _faults
from ...observability import events as _obs_events
from ...observability import metrics as _obs_metrics
from .scheduler import QueueFull

__all__ = ["ServingRouter", "Overloaded", "ROUTER_PHASES"]

#: phase a router's pool can be pinned to (DESIGN-SERVING.md
#: §Disaggregated tier).  None = classic phase-agnostic pool of
#: "both"-role replicas; "prefill"/"decode" pools refuse replicas of
#: any other role at spawn and judge their own scaling signal —
#: prefill on admission queue depth, decode on windowed inter-token
#: p99 (``serving_intertoken_s``) instead of request latency.
ROUTER_PHASES = (None, "prefill", "decode")


class Overloaded(QueueFull):
    """The router is shedding admissions: every replica's queue is
    full, or the SLO policy turned shedding on.  Subclasses
    :class:`QueueFull` so existing backpressure handling upstream of
    ``LLMServer`` covers the router unchanged."""


def _window_cum(prev, cur):
    """Cumulative bucket counts of the observations BETWEEN two
    cumulative histogram snapshots (``Histogram.collect()`` shape) —
    a diff of cumulatives is itself cumulative."""
    cur_cum = [c for _, c in cur.get("buckets", [])]
    prev_cum = ([c for _, c in prev.get("buckets", [])]
                if prev else [])
    if len(prev_cum) != len(cur_cum):
        prev_cum = [0] * len(cur_cum)
    return [max(c - p, 0) for p, c in zip(prev_cum, cur_cum)]


def _quantile_from_cum(edges: List[float], cum: List[float],
                       q: float) -> Optional[float]:
    """q-quantile from cumulative bucket counts with linear
    interpolation inside the landing bucket, exactly like
    ``Histogram.quantile`` (the +Inf bucket clamps to the top finite
    edge).  None when the window saw no observations — absence of
    traffic is not a latency."""
    n = cum[-1] if cum else 0
    if n <= 0:
        return None
    rank = q * n
    prev_c = 0.0
    for i, c in enumerate(cum):
        if c >= rank and c > prev_c:
            lo = 0.0 if i == 0 else float(edges[i - 1])
            hi = float(edges[i] if i < len(edges) - 1 else edges[-2])
            frac = (rank - prev_c) / (c - prev_c)
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        prev_c = c
    return float(edges[-2]) if len(edges) > 1 else None


def _delta_quantile(prev, cur, q: float) -> Optional[float]:
    """q-quantile of one histogram's observations between two
    snapshots (unit-tested; ``_signals`` runs the same math over the
    replica-merged window)."""
    return _quantile_from_cum([e for e, _ in cur.get("buckets", [])],
                              _window_cum(prev, cur), q)


class _Replica:
    """One managed ``LLMServer`` plus the router's view of it."""

    _seq = 0

    def __init__(self, server):
        _Replica._seq += 1
        self.name = f"replica-{_Replica._seq}"
        self.server = server
        self.draining = False
        # last cumulative latency snapshot, for the windowed p99 diff
        self.last_latency: Optional[Dict[str, Any]] = None

    # -- host-only signal reads (materialize=False everywhere) ------------
    @property
    def alive(self) -> bool:
        """Pump thread still running (a crashed replica must stop
        receiving admissions and be reaped; stub servers without the
        property count as alive)."""
        return bool(getattr(self.server, "running", True))

    @property
    def queue_depth(self) -> int:
        # accepted-but-unseated migrations ARE queue depth on a
        # decode replica: same admission backlog, different door
        eng = self.server.engine
        return (eng.scheduler.queue_depth
                + int(getattr(eng, "pending_migrations", 0)))

    @property
    def active(self) -> int:
        return self.server.engine.active_count

    @property
    def load(self) -> int:
        return self.queue_depth + self.active

    def signal_snapshot(self, hist_attr: str) -> Dict[str, Any]:
        """Cumulative snapshot of this replica's SLO histogram —
        ``_h_latency`` (classic/prefill pools) or ``_h_intertoken``
        (decode pools)."""
        return getattr(self.server.engine, hist_attr).collect(
            materialize=False)


class ServingRouter:
    """Admission router + SLO-driven autoscaler over ``LLMServer``
    replicas.

    ``replica_factory`` is a zero-arg callable returning a RUNNING
    ``LLMServer`` (pre-warmed factories make spawns cheap — see the
    README quickstart).  ``decision_interval_s=0`` disables the
    background loop; tests drive :meth:`control_round` directly.
    """

    #: knob surface of :meth:`to_config` / :meth:`from_config` — the
    #: exported-profile round-trip (every knob consumed or refused,
    #: same contract as the fleet DistributedStrategy)
    CONFIG_KNOBS = ("phase", "min_replicas", "max_replicas",
                    "slo_p99_s", "scale_up_queue_depth",
                    "scale_down_queue_depth", "windows_up",
                    "windows_down", "cooldown_s",
                    "decision_interval_s", "drain_relief_rate",
                    "predictive_scale_rate")

    def __init__(self, replica_factory: Callable[[], Any], *,
                 phase: Optional[str] = None,
                 min_replicas: int = 1, max_replicas: int = 2,
                 slo_p99_s: Optional[float] = None,
                 scale_up_queue_depth: float = 4.0,
                 scale_down_queue_depth: float = 0.5,
                 windows_up: int = 2, windows_down: int = 8,
                 cooldown_s: float = 5.0,
                 decision_interval_s: float = 0.25,
                 drain_relief_rate: float = 0.0,
                 predictive_scale_rate: float = 0.0,
                 metrics_port: Optional[int] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if phase not in ROUTER_PHASES:
            raise ValueError(
                f"phase {phase!r} is not one of {ROUTER_PHASES}")
        self.phase = phase
        # decode pools judge the SLO on the inter-token gap (the
        # steady-state jitter disaggregation exists to protect);
        # everything else judges request latency
        self._hist_attr = ("_h_intertoken" if phase == "decode"
                           else "_h_latency")
        self._factory = replica_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.slo_p99_s = (None if slo_p99_s is None
                          else float(slo_p99_s))
        self.scale_up_queue_depth = float(scale_up_queue_depth)
        self.scale_down_queue_depth = float(scale_down_queue_depth)
        self.windows_up = max(int(windows_up), 1)
        self.windows_down = max(int(windows_down), 1)
        self.cooldown_s = float(cooldown_s)
        self.decision_interval_s = float(decision_interval_s)
        # drain-relief (ROADMAP fleet remainder): when the per-replica
        # queue is FALLING at >= this rate (requests per round), depth
        # and shed evidence are discounted — a burst already draining
        # should not latch shed state.  0 = off (level-only policy,
        # bit-identical to before); SLO violation always counts.
        self.drain_relief_rate = float(drain_relief_rate)
        # predictive scale-UP: the same queue-depth derivative read
        # the other way — a queue RISING at >= this rate (requests per
        # replica per round) is overload evidence before the level
        # crosses scale_up_queue_depth, so capacity starts spinning up
        # while the ramp is still shallow.  0 = off (level-only
        # policy, bit-identical to before); windows_up/cooldown still
        # gate the actual spawn, so one noisy sample never scales.
        self.predictive_scale_rate = float(predictive_scale_rate)
        self._prev_queue: Optional[int] = None
        self._lock = threading.Lock()
        self._replicas: List[_Replica] = []
        self._shedding = False
        self._sheds_in_window = 0   # queue-full sheds since last round
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_t: float = -float("inf")
        self._last_p99: Optional[float] = None
        self._closed = False
        reg = _obs_metrics.registry()
        # phase-pinned pools label their children so two routers (a
        # disaggregated deployment runs one per phase) never write
        # one unlabeled child; a classic router keeps the unlabeled
        # names for backwards-compatible dashboards
        labels = {"phase": phase} if phase is not None else None
        self._obs_labels = labels
        self._g_replicas = reg.gauge(
            "serving_replicas",
            "live (non-draining) LLMServer replicas behind the "
            "router", labels=labels)
        self._g_p99 = reg.gauge(
            "router_p99_s",
            "windowed p99 of the pool's SLO signal (request latency; "
            "inter-token gap for decode pools); absent when the "
            "window saw no observations", labels=labels)
        self._g_queue = reg.gauge(
            "router_queue_depth",
            "waiting requests summed across replicas "
            "(pending migrations included)", labels=labels)
        self._c_requests = reg.counter(
            "router_requests_total", "admissions routed to a replica",
            labels=labels)
        self._c_shed = reg.counter(
            "router_shed_total",
            "admissions shed at the router door (Overloaded)",
            labels=labels)
        self._c_up = reg.counter(
            "router_scale_ups_total", "replicas spawned by the SLO "
            "control loop", labels=labels)
        self._c_down = reg.counter(
            "router_scale_downs_total", "replicas retired by the SLO "
            "control loop", labels=labels)
        for _ in range(self.min_replicas):
            self._spawn_replica(reason="min_replicas")
        self._g_replicas.set(len(self._replicas))
        self._metrics_server = None
        if metrics_port is not None:
            from ...observability import http as _obs_http
            self._metrics_server = _obs_http.serve(int(metrics_port))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.decision_interval_s > 0:
            self._thread = threading.Thread(
                target=self._control_loop,
                name="paddle-tpu-serving-router", daemon=True)
            self._thread.start()

    # -- capacity ----------------------------------------------------------
    def _spawn_replica(self, reason: str) -> _Replica:
        """Build one replica through the factory; the ``replica.spawn``
        fault site runs FIRST so chaos can fail the spawn path itself
        (the control loop survives and retries after cooldown)."""
        _faults.fault_point("replica.spawn",
                            n=len(self._replicas) + 1, reason=reason)
        server = self._factory()
        if self.phase is not None:
            # exported-knob contract (DistributedStrategy class): a
            # phase the replica can't honor is REFUSED loudly — a
            # "decode pool" quietly running both-role replicas would
            # re-admit prefill into the program this tier exists to
            # protect
            role = getattr(server, "role", "both")
            if role != self.phase:
                try:
                    server.close(unregister_metrics=True)
                except Exception:  # noqa: BLE001
                    pass
                raise ValueError(
                    f"router phase {self.phase!r} refused: "
                    f"replica_factory built a {role!r}-role server "
                    f"(pass role={self.phase!r} to the LLMServer)")
        rep = _Replica(server)
        with self._lock:
            self._replicas.append(rep)
        return rep

    def _live(self) -> List["_Replica"]:
        """Routable replicas (lock held by caller NOT required —
        takes it): not draining, pump alive, least-loaded first."""
        with self._lock:
            reps = [r for r in self._replicas
                    if not r.draining and r.alive]
        return sorted(reps, key=lambda r: r.load)

    @property
    def replicas(self) -> List[Any]:
        """Live (non-draining) replica servers, least-loaded first."""
        return [r.server for r in self._live()]

    @property
    def num_replicas(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas
                       if not r.draining and r.alive)

    @property
    def shedding(self) -> bool:
        return self._shedding

    def windowed_p99_s(self) -> Optional[float]:
        """p99 over the completions of the last decision window (None
        when that window saw none)."""
        return self._last_p99

    # -- front door --------------------------------------------------------
    def submit(self, prompt_ids, max_tokens: int, stream_cb=None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed=None):
        """Route one request to the least-loaded replica; returns the
        request future.  Sampling kwargs forward to
        ``LLMServer.submit`` (seeded sampling is replica-independent
        by construction — keys are (seed, position) functions, so
        routing does not affect output).  Raises :class:`Overloaded`
        when the router is shedding (SLO policy) or every replica's
        queue is full."""
        if self._closed:
            raise RuntimeError("router closed")
        if self.phase == "decode":
            raise ValueError(
                "decode-phase router admits only migrations "
                "(submit_migration); route prompts to the prefill "
                "pool — DESIGN-SERVING.md §Disaggregated tier")
        reps = self._live()
        if not reps:
            raise RuntimeError("router has no live replicas")
        if self._shedding and not _faults.should_drop(
                "router.shed", depth=sum(r.queue_depth for r in reps)):
            # a POLICY shed is the state doing its job, not fresh
            # overload evidence — feeding it back into the signal
            # would latch shedding on for as long as clients retry
            self._c_shed.inc()
            raise Overloaded(
                "router is shedding: SLO policy is on and capacity "
                "cannot grow — retry with backoff upstream")
        last_exc: Optional[Exception] = None
        for rep in reps:
            try:
                fut = rep.server.submit(prompt_ids, max_tokens,
                                        stream_cb=stream_cb,
                                        temperature=temperature,
                                        top_k=top_k, top_p=top_p,
                                        seed=seed)
            except QueueFull as e:
                last_exc = e
                continue
            self._c_requests.inc()
            return fut
        # every queue full: this IS a shed, whatever the policy state
        self._note_shed()
        raise Overloaded(
            f"all {len(reps)} replica queues full "
            f"({last_exc})") from last_exc

    def submit_migration(self, mig) -> Any:
        """Route one prefill→decode migration to the least-loaded
        replica that will take it (ISSUE-16 failover contract: target
        full → next-least-loaded; every target full →
        :class:`Overloaded`, counted as a shed — the caller parks and
        retries).  Returns the replica server that accepted."""
        if self._closed:
            raise RuntimeError("router closed")
        if self.phase == "prefill":
            raise ValueError(
                "prefill-phase router cannot accept migrations: its "
                "replicas never decode")
        reps = self._live()
        if not reps:
            raise RuntimeError("router has no live replicas")
        last_exc: Optional[Exception] = None
        for rep in reps:
            try:
                rep.server.submit_migration(mig)
            except QueueFull as e:
                last_exc = e
                continue
            self._c_requests.inc()
            return rep.server
        self._note_shed()
        raise Overloaded(
            f"all {len(reps)} decode replicas full "
            f"({last_exc})") from last_exc

    def _note_shed(self):
        """Count a QUEUE-FULL shed on the registry AND as overload
        evidence for the next decision round: queue-depth *samples*
        miss a burst that fills and drains between two 10 Hz rounds,
        but the rejections it forced are integral evidence the loop
        must not lose (verify-drive catch: 76 door-sheds in <0.2 s
        were invisible to the sampled queue depth — no scale-up,
        nothing on the ring)."""
        self._c_shed.inc()
        with self._lock:
            self._sheds_in_window += 1

    # -- control loop ------------------------------------------------------
    def _signals(self) -> Dict[str, Any]:
        """One host-only sample of the registry-backed signals the
        policy judges on (no device syncs — materialize=False)."""
        with self._lock:
            reps = [r for r in self._replicas
                    if not r.draining and r.alive]
            shed_delta, self._sheds_in_window = \
                self._sheds_in_window, 0
        queue = sum(r.queue_depth for r in reps)
        active = sum(r.active for r in reps)
        # windowed p99: diff every live replica's cumulative SLO
        # histogram (latency; inter-token for decode pools) against
        # its previous snapshot and merge the window counts (bucket
        # edges are shared — one registry name, one fixed grid, so
        # cumulative diffs add elementwise)
        merged_cum: Optional[List[float]] = None
        edges: Optional[List[float]] = None
        for r in reps:
            cur = r.signal_snapshot(self._hist_attr)
            prev, r.last_latency = r.last_latency, cur
            cum = _window_cum(prev, cur)
            if merged_cum is None:
                merged_cum = cum
                edges = [e for e, _ in cur.get("buckets", [])]
            elif len(cum) == len(merged_cum):
                merged_cum = [a + b for a, b in zip(merged_cum, cum)]
        p99 = (_quantile_from_cum(edges, merged_cum, 0.99)
               if merged_cum and edges else None)
        self._last_p99 = p99
        # queue-depth derivative: requests gained (+) or drained (-)
        # since the previous sample — the drain-relief policy's
        # evidence; first sample has no baseline, so delta 0
        prev, self._prev_queue = self._prev_queue, queue
        return {"replicas": len(reps), "queue_depth": queue,
                "active": active, "p99_s": p99,
                "shed_delta": shed_delta,
                "queue_delta": (0 if prev is None else queue - prev)}

    def control_round(self) -> Dict[str, Any]:
        """ONE policy decision over one signal sample (the background
        loop calls this every ``decision_interval_s``; tests call it
        directly).  Returns the sample it judged, with the decision
        annotated."""
        sig = self._signals()
        n = sig["replicas"]
        self._g_queue.set(sig["queue_depth"])
        self._g_p99.set(sig["p99_s"])
        per_rep = sig["queue_depth"] / max(n, 1)
        slo_violated = (self.slo_p99_s is not None
                        and sig["p99_s"] is not None
                        and sig["p99_s"] > self.slo_p99_s)
        # sheds since the last round are overload evidence too: a
        # burst that fills AND drains every queue between two rounds
        # never shows up in the sampled depth, but the rejections it
        # forced did happen.  Drain relief scales that evidence with
        # the depth DERIVATIVE: a queue already falling faster than
        # drain_relief_rate per replica per round is a burst on its
        # way out, and holding shed latched against it rejects
        # traffic the pool is about to absorb anyway — only a live
        # SLO violation overrides the relief
        draining = (self.drain_relief_rate > 0
                    and sig["queue_delta"] < 0
                    and (-sig["queue_delta"]) / max(n, 1)
                    >= self.drain_relief_rate)
        # predictive scale-up: the same derivative read the other way
        # — a steep enough RISE is overload evidence before the level
        # is (rising and draining are mutually exclusive by sign, so
        # the relief conjunct below never cancels it)
        rising = (self.predictive_scale_rate > 0
                  and sig["queue_delta"] > 0
                  and sig["queue_delta"] / max(n, 1)
                  >= self.predictive_scale_rate)
        overloaded = (((per_rep > self.scale_up_queue_depth
                        or sig["shed_delta"] > 0 or rising)
                       and not draining)
                      or slo_violated)
        idle = (per_rep <= self.scale_down_queue_depth
                and not slo_violated and sig["shed_delta"] == 0
                and not rising)
        self._up_streak = self._up_streak + 1 if overloaded else 0
        self._down_streak = self._down_streak + 1 if idle else 0
        now = time.monotonic()
        cooled = now - self._last_scale_t >= self.cooldown_s
        decision = "hold"
        if (overloaded and self._up_streak >= self.windows_up
                and n < self.max_replicas and cooled):
            decision = self._scale_up(sig)
        elif (idle and self._down_streak >= self.windows_down
                and n > self.min_replicas and cooled):
            decision = self._scale_down(sig)
        # shedding state: overload that capacity can't absorb (maxed
        # out or mid-cooldown) sheds at the door; any non-overloaded
        # round turns it back off
        want_shed = (overloaded and self._up_streak >= self.windows_up
                     and (n >= self.max_replicas or not cooled))
        if want_shed and not self._shedding:
            self._shedding = True
            _obs_events.record("shed_on", queue_depth=sig["queue_depth"],
                               p99_s=sig["p99_s"], replicas=n,
                               shed_recent=sig["shed_delta"])
        elif self._shedding and not overloaded:
            self._shedding = False
            _obs_events.record("shed_off",
                               queue_depth=sig["queue_depth"],
                               p99_s=sig["p99_s"], replicas=n)
        self._reap_draining()
        self._reap_dead()
        self._g_replicas.set(self.num_replicas)
        sig["decision"] = decision
        return sig

    def _scale_up(self, sig: Dict[str, Any]) -> str:
        try:
            self._spawn_replica(reason="overload")
        except Exception as e:  # noqa: BLE001 — injected or OOM: the
            # router survives on current capacity and retries after
            # cooldown (shedding covers the gap)
            self._last_scale_t = time.monotonic()
            _obs_events.record("scale_up_failed",
                               error=f"{type(e).__name__}: {e}")
            return "scale_up_failed"
        self._last_scale_t = time.monotonic()
        self._up_streak = 0
        self._c_up.inc()
        _obs_events.record("scale_up", replicas=self.num_replicas,
                           queue_depth=sig["queue_depth"],
                           p99_s=sig["p99_s"])
        return "scale_up"

    def _scale_down(self, sig: Dict[str, Any]) -> str:
        with self._lock:
            live = [r for r in self._replicas if not r.draining]
            victim = min(live, key=lambda r: r.load)
            victim.draining = True
        self._last_scale_t = time.monotonic()
        self._down_streak = 0
        self._c_down.inc()
        _obs_events.record("scale_down", victim=victim.name,
                           replicas=self.num_replicas,
                           queue_depth=sig["queue_depth"])
        return "scale_down"

    def _reap_draining(self):
        """Close drained victims once their in-flight work finishes
        (no new admissions reach a draining replica, so load only
        falls).  Registry children are reclaimed — replica churn is
        the router's normal operation, and unbounded dead-engine
        series would bloat every scrape."""
        with self._lock:
            done = [r for r in self._replicas
                    if r.draining and r.load == 0]
            self._replicas = [r for r in self._replicas
                              if r not in done]
        for r in done:
            try:
                r.server.close(unregister_metrics=True)
            except Exception:  # noqa: BLE001 — a wedged close must
                # not stall the control loop
                pass
            _obs_events.record("replica_retired", victim=r.name)

    def _reap_dead(self):
        """Remove replicas whose pump crashed (their in-flight futures
        already failed via ``LLMServer._fail_all``) and respawn back to
        ``min_replicas`` — a died-mid-prompt prefill replica must not
        leave the pool permanently short (the disaggregated failover
        path re-admits its lost prompts through the NEW capacity)."""
        with self._lock:
            dead = [r for r in self._replicas
                    if not r.draining and not r.alive]
            self._replicas = [r for r in self._replicas
                              if r not in dead]
        for r in dead:
            try:
                r.server.close(unregister_metrics=True)
            except Exception:  # noqa: BLE001
                pass
            _obs_events.record("replica_died", victim=r.name)
        while dead and self.num_replicas < self.min_replicas:
            try:
                self._spawn_replica(reason="replace_dead")
            except Exception as e:  # noqa: BLE001 — chaos-injected
                # spawn failure: stay short, retry next round
                _obs_events.record("respawn_failed",
                                   error=f"{type(e).__name__}: {e}")
                break

    def _control_loop(self):
        while not self._stop.wait(self.decision_interval_s):
            try:
                self.control_round()
            except Exception as e:  # noqa: BLE001 — one bad round
                # (mid-close races included) must not kill the loop
                _obs_events.record(
                    "control_round_failed",
                    error=f"{type(e).__name__}: {e}")

    # -- profile round-trip ------------------------------------------------
    def to_config(self) -> Dict[str, Any]:
        """Export this router's policy knobs as a plain dict — the
        profile surface a deployment config serializes.  Round-trips
        through :meth:`from_config` bit-for-bit."""
        return {k: getattr(self, k) for k in self.CONFIG_KNOBS}

    @classmethod
    def from_config(cls, config: Dict[str, Any],
                    replica_factory: Callable[[], Any],
                    **kwargs) -> "ServingRouter":
        """Build a router from an exported profile.  Every knob is
        consumed or REFUSED: an unknown key raises instead of
        silently no-opping (the DistributedStrategy knob contract —
        a typo'd SLO in a profile must fail deploy, not ship a router
        that never scales)."""
        unknown = sorted(set(config) - set(cls.CONFIG_KNOBS))
        if unknown:
            raise ValueError(
                f"unknown router knob(s) {unknown} refused; known "
                f"knobs: {sorted(cls.CONFIG_KNOBS)}")
        merged = dict(config)
        merged.update(kwargs)
        return cls(replica_factory, **merged)

    # -- lifecycle ---------------------------------------------------------
    @property
    def metrics_port(self) -> Optional[int]:
        return (None if self._metrics_server is None
                else self._metrics_server.port)

    def close(self):
        """Stop the control loop and close every replica (their
        pending futures fail per ``LLMServer.close``)."""
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            reps, self._replicas = list(self._replicas), []
        for r in reps:
            try:
                r.server.close(unregister_metrics=True)
            except Exception:
                pass
        self._g_replicas.set(0)
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None

    def __enter__(self) -> "ServingRouter":
        return self

    def __exit__(self, *exc):
        self.close()
        return False
