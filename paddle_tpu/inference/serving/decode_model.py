"""Pure-functional GPT prefill/decode over an extracted weight tree.

The training-side ``GPTForCausalLM`` forward recomputes attention over
the whole sequence every call — right for training, hopeless for
serving.  This module lowers the same weights into cache-aware pure
functions the serving engine can compile once and dispatch forever:

- :func:`extract_decode_params` — Layer tree → plain jax-array pytree
  (device-resident; passed into the jitted steps as an argument, so a
  hapi-trained network exports to the server without copies).
- :func:`prefill_forward` — full-prompt forward at a bucket length,
  returning per-layer K/V for the page writes, plus the first greedy
  token.  One compile per prompt bucket (``io/bucketing.py`` sizes).
- :func:`decode_forward` — ONE token per request across the whole
  batch against the paged pool; the pool is appended in-place (donated
  by the caller's jit) and attention runs ragged over the page table.
  This is the single program the continuous-batching engine dispatches.
- :func:`reference_decode` — slow per-request sequential decode with a
  dense cache; the exactness oracle for tests, NOT a serving path.

Numerics mirror the training stack deliberately: LayerNorm statistics
in f32 (``ops/nn_ops.layer_norm``), tanh-approximate GELU, attention
scale ``1/sqrt(Dh)``, and the qkv fused projection split in the same
``[3, H, Dh]`` feature-major order ``GPTAttention.forward`` uses — so
extracted-weight logits match the training forward to float tolerance.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .kv_cache import (gather_pages, paged_append, SCRATCH_BLOCK,
                       write_prompt_pages)
from .ragged_attention import (causal_prefill_attention,
                               chunked_prefill_attention,
                               paged_decode_attention,
                               ragged_decode_attention)
from .sampling import sample_tokens


@dataclass(frozen=True)
class ServingModelConfig:
    """Static model geometry baked into the compiled serving steps."""
    num_layers: int
    num_heads: int
    head_dim: int
    hidden_size: int
    vocab_size: int
    max_position: int
    ln_epsilon: float = 1e-5

    @classmethod
    def from_gpt_config(cls, cfg) -> "ServingModelConfig":
        return cls(num_layers=cfg.num_hidden_layers,
                   num_heads=cfg.num_attention_heads,
                   head_dim=cfg.hidden_size // cfg.num_attention_heads,
                   hidden_size=cfg.hidden_size,
                   vocab_size=cfg.vocab_size,
                   max_position=cfg.max_position_embeddings,
                   ln_epsilon=cfg.layer_norm_epsilon)


def extract_decode_params(network):
    """``GPTForCausalLM`` → plain pytree of jax arrays for the compiled
    serving steps.  Reads the live parameter values (post-training,
    post-``sync_to_layers``); the returned tree is an ordinary jit
    argument, so server weights can be refreshed by re-extracting."""
    net = network
    if hasattr(net, "gpt"):          # GPTForCausalLM → GPTModel
        gpt = net.gpt
    else:
        raise TypeError(
            f"serving decode expects a GPTForCausalLM-shaped network "
            f"(got {type(net).__name__}); wrap custom models in the "
            "same .gpt/.embeddings/.layers layout")
    emb = gpt.embeddings
    params = {
        "wte": emb.word_embeddings.weight._value,
        "wpe": emb.position_embeddings.weight._value,
        "lnf_w": gpt.final_norm.weight._value,
        "lnf_b": gpt.final_norm.bias._value,
        "layers": [],
    }
    for layer in gpt.layers:
        params["layers"].append({
            "ln1_w": layer.ln1.weight._value,
            "ln1_b": layer.ln1.bias._value,
            "wqkv": layer.attn.qkv_proj.weight._value,
            "bqkv": layer.attn.qkv_proj.bias._value,
            "wo": layer.attn.out_proj.weight._value,
            "bo": layer.attn.out_proj.bias._value,
            "ln2_w": layer.ln2.weight._value,
            "ln2_b": layer.ln2.bias._value,
            "w1": layer.mlp.fc1.weight._value,
            "b1": layer.mlp.fc1.bias._value,
            "w2": layer.mlp.fc2.weight._value,
            "b2": layer.mlp.fc2.bias._value,
        })
    return params


def _ln(x, w, b, eps):
    """f32-statistics LayerNorm matching ``ops/nn_ops.layer_norm``."""
    orig = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = out * w.astype(jnp.float32) + b.astype(jnp.float32)
    return out.astype(orig)


def _split_qkv(qkv, num_heads, head_dim):
    """Fused projection output → (q, k, v), each ``[..., H, Dh]`` —
    same ``[3, H, Dh]`` feature-major split as ``GPTAttention``."""
    lead = qkv.shape[:-1]
    qkv = qkv.reshape(*lead, 3, num_heads, head_dim)
    take = lambda i: qkv[..., i, :, :]  # noqa: E731
    return take(0), take(1), take(2)


def _mlp(x, lp, eps):
    h = _ln(x, lp["ln2_w"], lp["ln2_b"], eps)
    h = jax.nn.gelu(h @ lp["w1"] + lp["b1"], approximate=True)
    return h @ lp["w2"] + lp["b2"]


# ---------------------------------------------------------------------------
# prefill
# ---------------------------------------------------------------------------
def prefill_group_forward(params, cfg: ServingModelConfig, ids,
                          lengths, temperature, top_k, top_p, seed):
    """Batched same-bucket prefill: one dispatch for a whole bucket
    group (DESIGN-SERVING.md §Long-context tier).

    ``ids`` ``[G, Lb]`` int32 (each prompt right-padded to the shared
    bucket); ``lengths`` ``[G]`` int32 real prompt lengths; sampling
    vectors ``[G]`` (see ``sampling.sample_tokens``; the first token's
    PRNG position is the prompt length).  Returns
    ``(kv [L, 2, G, Lb, H, Dh], first_tokens [G], last_logits
    [G, V])``.  Rows are independent under causal attention, so a
    group member's rows are bit-identical to its solo prefill; padded
    group rows (length 0) emit garbage the engine ignores.
    """
    G, Lb = ids.shape
    pos = jnp.arange(Lb, dtype=jnp.int32)
    x = params["wte"][ids] + params["wpe"][pos][None]
    kvs = []
    for lp in params["layers"]:
        h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_epsilon)
        q, k, v = _split_qkv(h @ lp["wqkv"] + lp["bqkv"],
                             cfg.num_heads, cfg.head_dim)
        kvs.append(jnp.stack([k, v]))              # [2, G, Lb, H, Dh]
        attn = causal_prefill_attention(q, k, v)
        x = x + attn.reshape(G, Lb, cfg.hidden_size) @ lp["wo"] + lp["bo"]
        x = x + _mlp(x, lp, cfg.ln_epsilon)
    x = _ln(x, params["lnf_w"], params["lnf_b"], cfg.ln_epsilon)
    lengths = lengths.astype(jnp.int32)
    last_ix = jnp.maximum(lengths - 1, 0)
    last = jnp.take_along_axis(
        x, last_ix[:, None, None], axis=1)[:, 0]   # [G, D]
    logits = last @ params["wte"].T                # [G, V]
    first_tokens = sample_tokens(logits, temperature, top_k, top_p,
                                 seed, lengths)
    return jnp.stack(kvs), first_tokens, logits


def prefill_forward(params, cfg: ServingModelConfig, ids, length):
    """Full-prompt forward at a bucket length (single request, greedy
    first token — the historical entry; the engine dispatches
    :func:`prefill_group_forward`).

    ``ids`` ``[1, Lb]`` int32 (prompt right-padded to its bucket);
    ``length`` traced int32 scalar — the real prompt length.  Returns
    ``(kv, first_token, last_logits)`` where ``kv`` is
    ``[L, 2, Lb, H, Dh]`` ready for ``write_prompt_pages``,
    ``first_token`` is the greedy next token after the prompt, and
    ``last_logits`` ``[V]`` is its distribution (exactness tests).

    Causality makes bucket padding exact for the real positions: a
    padded row attends only backwards and is never attended to by any
    real row; its garbage K/V land in pages but are masked by length
    in every later ragged-decode read.
    """
    length = jnp.asarray(length, jnp.int32)
    kv, toks, logits = prefill_group_forward(
        params, cfg, ids, length[None],
        jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
        jnp.ones((1,), jnp.float32), jnp.zeros((1,), jnp.uint32))
    return kv[:, :, 0], toks[0], logits[0]


def chunk_prefill_forward(params, cfg: ServingModelConfig, pool,
                          ctx_table, ctx_len, ids, chunk_len,
                          chunk_blocks, temperature, top_k, top_p,
                          seed):
    """One prefill *chunk* against the paged pool: compute the chunk's
    K/V attending to already-cached context (prefix-cache hits and
    earlier chunks), write them into the chunk's pages, and emit the
    next-token logits of the chunk's last real position.

    ``pool`` ``[L, 2, NB, BS, H, Dh]`` (caller's jit donates it);
    ``ctx_table`` ``[1, NBctx]`` int32 — page-table slice covering the
    existing context, bucketed so the trace count stays logarithmic;
    ``ctx_len`` int32 scalar — real cached tokens; ``ids`` ``[1, Cb]``
    int32 chunk tokens right-padded to the chunk bucket; ``chunk_len``
    int32 scalar real chunk tokens; ``chunk_blocks`` ``[Cb // BS]``
    int32 destination pages (tail entries SCRATCH_BLOCK); sampling
    scalars as in :func:`prefill_group_forward` (only meaningful on a
    prompt's final chunk, whose last position emits the first
    generated token at PRNG position ``ctx_len + chunk_len``).
    Returns ``(pool, first_token, last_logits [V])``.
    """
    B, Cb = ids.shape
    ctx_len = jnp.asarray(ctx_len, jnp.int32)
    chunk_len = jnp.asarray(chunk_len, jnp.int32)
    pos = jnp.minimum(ctx_len + jnp.arange(Cb, dtype=jnp.int32),
                      cfg.max_position - 1)
    x = params["wte"][ids] + params["wpe"][pos][None]
    kvs = []
    for li, lp in enumerate(params["layers"]):
        h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_epsilon)
        q, k, v = _split_qkv(h @ lp["wqkv"] + lp["bqkv"],
                             cfg.num_heads, cfg.head_dim)
        kvs.append(jnp.stack([k[0], v[0]]))        # [2, Cb, H, Dh]
        k_ctx, v_ctx = gather_pages(pool, li, ctx_table)
        attn = chunked_prefill_attention(q, k_ctx, v_ctx, ctx_len,
                                         k, v)
        x = x + attn.reshape(B, Cb, cfg.hidden_size) @ lp["wo"] + lp["bo"]
        x = x + _mlp(x, lp, cfg.ln_epsilon)
    pool = write_prompt_pages(pool, jnp.stack(kvs), chunk_blocks)
    x = _ln(x, params["lnf_w"], params["lnf_b"], cfg.ln_epsilon)
    last = x[0, jnp.maximum(chunk_len - 1, 0)]     # [D]
    logits = last @ params["wte"].T                # [V]
    tok = sample_tokens(
        logits[None],
        jnp.asarray(temperature, jnp.float32)[None],
        jnp.asarray(top_k, jnp.int32)[None],
        jnp.asarray(top_p, jnp.float32)[None],
        jnp.asarray(seed, jnp.uint32)[None],
        (ctx_len + chunk_len)[None])[0]
    return pool, tok, logits


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------
def decode_forward(params, cfg: ServingModelConfig, pool, page_table,
                   lengths, tokens, write_ok, attention="gather"):
    """ONE decode token per request over the paged pool.

    ``pool`` ``[L, 2, NB, BS, H, Dh]`` (caller's jit donates it);
    ``page_table`` ``[B, MAXNB]`` int32; ``lengths`` ``[B]`` int32 —
    tokens already in cache per request (the new token's position);
    ``tokens`` ``[B]`` int32 — the input token per request;
    ``write_ok`` ``[B]`` bool — rows with ``False`` (empty slot, done
    request) write to the scratch block and their output is garbage
    the engine masks.  ``attention`` is the *resolved* implementation
    behind the ``ragged_attention.paged_decode_attention`` seam
    ("gather" reference or the fused "pallas" kernel) — a static
    trace-time choice baked into the engine's one decode program.
    Returns ``(pool, logits [B, V])``.
    """
    L, _, NB, BS, H, Dh = pool.shape
    B, MAXNB = page_table.shape
    lengths = lengths.astype(jnp.int32)
    # position of the incoming token; clamp keeps a stale (done but
    # not yet polled) slot's growing length from indexing out of range
    pos = jnp.minimum(lengths, cfg.max_position - 1)
    write_pos = jnp.minimum(lengths, MAXNB * BS - 1)
    blk_slot = jnp.minimum(write_pos // BS, MAXNB - 1)
    block_ids = jnp.take_along_axis(
        page_table, blk_slot[:, None], axis=1)[:, 0]
    block_ids = jnp.where(write_ok, block_ids, SCRATCH_BLOCK)
    offsets = write_pos % BS
    x = params["wte"][tokens] + params["wpe"][pos]          # [B, D]
    for li, lp in enumerate(params["layers"]):
        h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_epsilon)
        q, k, v = _split_qkv(h @ lp["wqkv"] + lp["bqkv"],
                             cfg.num_heads, cfg.head_dim)
        pool = paged_append(pool, li, k, v, block_ids, offsets)
        # context includes the token just appended
        attn = paged_decode_attention(pool, li, page_table,
                                      lengths + 1, q, mode=attention)
        x = x + attn.reshape(B, cfg.hidden_size) @ lp["wo"] + lp["bo"]
        x = x + _mlp(x, lp, cfg.ln_epsilon)
    x = _ln(x, params["lnf_w"], params["lnf_b"], cfg.ln_epsilon)
    logits = x @ params["wte"].T                            # [B, V]
    return pool, logits


def spec_score_forward(params, cfg: ServingModelConfig, pool,
                       page_table, lengths, tokens, write_ok,
                       attention="gather"):
    """Score all ``S = k+1`` positions of a speculative window in ONE
    batched forward (DESIGN-SERVING.md §Speculative tier).

    ``tokens`` ``[B, S]`` int32 — the incoming token plus the draft's
    k proposals per request; ``lengths`` ``[B]`` — cache tokens before
    the window.  The window is flattened into the batch axis and fed
    through :func:`decode_forward` unchanged: window slot ``(b, i)``
    becomes a row with the same page table and length ``n_b + i``.
    Because ``decode_forward`` appends every row's K/V *before* the
    layer's attention read, row ``(b, i)`` attends over positions
    ``0..n_b+i`` — which includes the K/V rows ``(b, 0..i)`` just
    wrote — so the semantics are exactly causal over the proposed
    suffix, with no new attention math and the same grouped page-write
    scatter committing the window.  Rows whose window position would
    land past the page table's reach (look-ahead at the max-context
    edge) are routed to the scratch block instead of clamp-colliding
    with real cache.  Returns ``(pool, logits [B, S, V])``.
    """
    _, _, _, BS, _, _ = pool.shape
    B, MAXNB = page_table.shape
    S = tokens.shape[1]
    offs = jnp.arange(S, dtype=jnp.int32)
    flat_len = (lengths.astype(jnp.int32)[:, None]
                + offs[None]).reshape(-1)              # [B*S]
    flat_ok = (jnp.repeat(write_ok, S)
               & (flat_len < MAXNB * BS))
    pool, logits = decode_forward(
        params, cfg, pool,
        jnp.repeat(page_table, S, axis=0),
        flat_len, tokens.reshape(-1), flat_ok, attention=attention)
    return pool, logits.reshape(B, S, -1)


# ---------------------------------------------------------------------------
# sequential oracle (tests only)
# ---------------------------------------------------------------------------
def reference_decode(params, cfg: ServingModelConfig, prompt_ids,
                     num_tokens, temperature=0.0, top_k=0,
                     top_p=1.0, seed=0):
    """Per-request sequential decode with a dense cache (greedy by
    default; sampled when ``temperature > 0``).

    ``prompt_ids``: 1-D int sequence.  Returns ``(tokens [num_tokens],
    logits [num_tokens, V])`` as jax arrays.  Unbatched, unpaged,
    unjitted — the exactness oracle the ragged batched path is tested
    against, sharing the same primitive helpers so the only deltas are
    batching, paging, and padded-axis reduction order.  Sampling
    derives the identical in-program keys as the serving engine
    (``fold_in(PRNGKey(seed), token_index)``), so a seeded sampled
    request must reproduce this oracle token for token.
    """

    def _pick(lg, position):
        return sample_tokens(
            lg[None],
            jnp.asarray(float(temperature), jnp.float32)[None],
            jnp.asarray(int(top_k), jnp.int32)[None],
            jnp.asarray(float(top_p), jnp.float32)[None],
            jnp.asarray(int(seed), jnp.uint32)[None],
            jnp.asarray(int(position), jnp.int32)[None])[0]

    ids = jnp.asarray(prompt_ids, dtype=jnp.int32)[None]    # [1, Lp]
    Lp = ids.shape[1]
    kv, tok, logits = prefill_forward(params, cfg, ids,
                                      jnp.int32(Lp))
    tok = _pick(logits, Lp)
    caches = [(kv[li, 0], kv[li, 1]) for li in
              range(cfg.num_layers)]                        # [T, H, Dh]
    out_toks = [tok]
    out_logits = [logits]
    for step in range(1, int(num_tokens)):
        pos = min(Lp + step - 1, cfg.max_position - 1)
        x = params["wte"][tok] + params["wpe"][pos]          # [D]
        x = x[None]                                          # [1, D]
        new_caches = []
        for li, lp in enumerate(params["layers"]):
            h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_epsilon)
            q, k, v = _split_qkv(h @ lp["wqkv"] + lp["bqkv"],
                                 cfg.num_heads, cfg.head_dim)
            ck = jnp.concatenate([caches[li][0], k], axis=0)
            cv = jnp.concatenate([caches[li][1], v], axis=0)
            new_caches.append((ck, cv))
            T = ck.shape[0]
            attn = ragged_decode_attention(
                q, ck[None], cv[None],
                jnp.full((1,), T, dtype=jnp.int32))
            x = x + attn.reshape(1, cfg.hidden_size) @ lp["wo"] \
                + lp["bo"]
            x = x + _mlp(x, lp, cfg.ln_epsilon)
        caches = new_caches
        x = _ln(x, params["lnf_w"], params["lnf_b"], cfg.ln_epsilon)
        lg = (x @ params["wte"].T)[0]
        tok = _pick(lg, Lp + step)
        out_toks.append(tok)
        out_logits.append(lg)
    return jnp.stack(out_toks), jnp.stack(out_logits)
