"""KV page migration: the transfer ticket between serving phases
(DESIGN-SERVING.md §Disaggregated tier).

Disaggregated serving runs prefill and decode on SEPARATE engines so
long-prompt admission never perturbs steady-state decode (PAPERS.md
arxiv 2605.25645).  The seam between them is this module: when a
prefill replica finishes a prompt, the request's finished pages plus
its sampling state leave that engine as a :class:`PageMigration` and
enter the decode replica's pool under NEW block ids — a page-table
remap, not a pointer handoff.

What must transfer for the handoff to be token-exact (test-pinned
against the single-engine oracle):

- the K/V pages of the full prompt, in table order (prefix-cache hit
  blocks first, then the request's own) — gathered from the source
  pool, scattered into freshly imported destination blocks;
- the prompt length (the sampling PRNG position counter continues
  from it) and the first generated token, still ON DEVICE (the decode
  replica's join consumes it as the next dispatch's input token);
- the resolved sampling state: ``temperature``/``top_k``/``top_p``
  and the request's RESOLVED seed.  Seeds default per-request
  (``Request.seed = id``), so the ticket carries the request object
  itself — re-deriving the seed on the decode side would change the
  sampled sequence.  Sampling keys are pure ``(seed, position)``
  functions, never slot/batch/engine functions, which is the whole
  reason a migrated request samples identically.

The device copy is two shape-stable jitted ops the engines own
(:func:`gather_request_pages` on the exporter — the pool is NOT
donated, other slots still live in it — and
:func:`scatter_request_pages` on the importer, destination pool
donated).  Block counts pad to the exporter's pow2 context buckets,
padding slots target ``SCRATCH_BLOCK`` on both sides: scatter
collisions land only in scratch, which nothing reads.  Neither side
syncs host with device — in process, a migration is one D2D copy
riding the dispatch queue (``check_host_sync.py`` holds this module
to the hot-loop contract).

A ticket is SINGLE-USE: :meth:`PageMigration.consume` refuses a
second import — the pages were freed on the source when the ticket
was cut, so a double import would seat two live requests on one
future and one stats record.

Multi-host: in-process the ticket holds device arrays; across hosts
the same ticket rides the fleet KV registry as the transfer
coordination plane — see the design doc for the protocol sketch
(gather → publish under the request's chain hash → importer fetch →
scatter), which reuses this exact export/import seam.
"""

from __future__ import annotations

from typing import Any, Dict, List

__all__ = ["MigrationError", "PageMigration",
           "gather_request_pages", "scatter_request_pages"]


class MigrationError(RuntimeError):
    """A page migration cannot be honored: geometry mismatch between
    pools, a consumed (already-imported) ticket, or an import on a
    role that never admits one."""


# ---------------------------------------------------------------------------
# pure pool ops (jitted by the engines; shape-stable per pow2 bucket)
# ---------------------------------------------------------------------------
def gather_request_pages(pool, block_ids):
    """Copy one request's pages out of a pool: ``pool``
    ``[L, 2, NB, BS, H, Dh]``, ``block_ids`` ``[nbb]`` int32 (padded
    to a pow2 bucket with SCRATCH_BLOCK) → ``[L, 2, nbb, BS, H, Dh]``.
    Whatever the padding gathers from scratch is never scattered onto
    a real destination block."""
    return pool[:, :, block_ids]


def scatter_request_pages(pool, kv, block_ids):
    """Land migrated pages in the destination pool under its OWN block
    ids: ``kv`` ``[L, 2, nbb, BS, H, Dh]`` from
    :func:`gather_request_pages`, ``block_ids`` ``[nbb]`` int32 with
    the padding tail at SCRATCH_BLOCK — duplicate scratch indices make
    the scatter order-dependent only inside scratch, which is never
    read."""
    return pool.at[:, :, block_ids].set(kv)


class PageMigration:
    """One request's pages + sampling state in flight between engines.

    Cut by the exporting (prefill) engine at prompt completion;
    consumed exactly once by the importing (decode) engine.  The
    source engine has already freed its copy of the pages when the
    ticket exists — the ticket OWNS the K/V until import.
    """

    __slots__ = ("request", "kv", "nb", "token", "t_start",
                 "geometry", "consumed", "source")

    def __init__(self, request, kv, nb: int, token, t_start: float,
                 geometry: Dict[str, Any], source: str = ""):
        self.request = request          # carries future/stats/seed
        self.kv = kv                    # [L, 2, nbb, BS, H, Dh] device
        self.nb = int(nb)               # real block count (<= nbb)
        self.token = token              # first generated token, device
        self.t_start = float(t_start)   # export wall clock (monotonic)
        self.geometry = dict(geometry)
        self.source = source            # exporting engine id (obs)
        self.consumed = False

    def check_geometry(self, engine) -> None:
        """Refuse an import the destination pool can never hold
        bit-exactly: pages are raw ``[BS, H, Dh]`` K/V slabs, so every
        shape component and the dtype must agree."""
        kvc = engine._kv
        want = {"num_layers": kvc.num_layers,
                "block_size": kvc.block_size,
                "num_heads": kvc.num_heads,
                "head_dim": kvc.head_dim,
                "dtype": str(kvc.pool.dtype)}
        if self.geometry != want:
            raise MigrationError(
                f"pool geometry mismatch: ticket {self.geometry} vs "
                f"destination {want} — migration requires identical "
                "block shape and dtype")

    def consume(self):
        """Single-use gate: returns the request, or refuses a second
        import (the source pages are gone; a double import would seat
        one future twice)."""
        if self.consumed:
            raise MigrationError(
                f"migration of request {self.request.id} already "
                "imported — tickets are single-use")
        self.consumed = True
        return self.request

    def __repr__(self):
        return (f"PageMigration(request={self.request.id}, "
                f"nb={self.nb}, consumed={self.consumed})")
