"""paddle.callbacks — top-level alias of the hapi callback family
(parity: upstream ``python/paddle/callbacks.py``, which re-exports
``paddle.hapi.callbacks``)."""

from ..hapi.callbacks import *  # noqa: F401,F403
from ..hapi import callbacks as _c

__all__ = list(getattr(_c, "__all__", [n for n in dir(_c)
                                       if n[0].isupper()]))
