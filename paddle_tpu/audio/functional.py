"""paddle.audio.functional (parity: python/paddle/audio/functional/):
windows, mel scale, filterbanks, dB conversion, DCT basis."""

from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor


def _wrap(v, dtype):
    return Tensor(jnp.asarray(v, dtype=dtype))


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype: str = "float64"):
    """Hann/Hamming/Blackman/... windows (upstream get_window subset).
    ``fftbins=True`` gives the periodic variant (DFT-even)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length + 1 if fftbins else win_length
    k = np.arange(n)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (n - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
             + 0.08 * np.cos(4 * np.pi * k / (n - 1)))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * k / (n - 1) - 1)
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = np.ones(n)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((k - (n - 1) / 2) / std) ** 2)
    elif name == "exponential":
        tau = args[0] if args else 1.0
        w = np.exp(-np.abs(k - (n - 1) / 2) / tau)
    elif name == "triang":
        w = 1.0 - np.abs((k - (n - 1) / 2) / (n / 2))
    else:
        raise ValueError(f"get_window: unknown window {name!r}")
    if fftbins:
        w = w[:-1]
    return _wrap(w, dtype)


def hz_to_mel(freq, htk: bool = False):
    """Hz → mel (Slaney by default, HTK optional — upstream parity)."""
    f = jnp.asarray(freq._value if isinstance(freq, Tensor) else freq,
                    jnp.float64)
    scalar = f.ndim == 0 and not isinstance(freq, Tensor)
    if htk:
        m = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        m = jnp.where(f >= min_log_hz,
                      min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                            / min_log_hz) / logstep,
                      mels)
    return float(m) if scalar else Tensor(m)


def mel_to_hz(mel, htk: bool = False):
    m = jnp.asarray(mel._value if isinstance(mel, Tensor) else mel,
                    jnp.float64)
    scalar = m.ndim == 0 and not isinstance(mel, Tensor)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                      freqs)
    return float(f) if scalar else Tensor(f)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float64"):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = jnp.linspace(lo, hi, n_mels, dtype=jnp.float64)
    return _wrap(mel_to_hz(Tensor(mels), htk)._value, dtype)


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float64"):
    return _wrap(jnp.linspace(0, sr / 2.0, 1 + n_fft // 2,
                              dtype=jnp.float64), dtype)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0,
                         f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney",
                         dtype: str = "float64"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    if f_max is None:
        f_max = sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)._value
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)._value
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return _wrap(weights, dtype)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """10*log10(S/ref) with optional dynamic-range clamp."""
    s = spect._value if isinstance(spect, Tensor) else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float64"):
    """DCT-II basis [n_mels, n_mfcc] (upstream create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[None, :]
    basis = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(n_mels)
        basis[:, 1:] *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return _wrap(basis, dtype)
