"""paddle.audio (parity: python/paddle/audio/ — functional features +
feature Layers over the signal/stft stack).

TPU-first: every feature is a pure jnp pipeline over the framework's
stft (one rfft matmul-class op XLA handles well), so Spectrogram /
MelSpectrogram / LogMelSpectrogram / MFCC run inside compiled train
steps (speech frontends train on-device instead of on the host)."""

from . import functional  # noqa
from . import features  # noqa
from .functional import (  # noqa
    get_window, hz_to_mel, mel_to_hz, mel_frequencies, fft_frequencies,
    compute_fbank_matrix, power_to_db, create_dct)
from .features import (  # noqa
    Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)
