"""paddle.audio.features (parity: python/paddle/audio/features/layers.py):
Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC as nn.Layers —
pure jnp pipelines over the framework stft, usable inside compiled
training steps."""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..nn import Layer
from ..tensor import Tensor
from .. import signal as _signal
from . import functional as F


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = F.get_window(window, self.win_length, fftbins=True,
                         dtype=dtype)
        self.register_buffer("window", w)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, hop_length=self.hop_length,
                            win_length=self.win_length,
                            window=self.window, center=self.center,
                            pad_mode=self.pad_mode)
        v = spec._value if isinstance(spec, Tensor) else spec
        mag = jnp.abs(v)
        if self.power != 1.0:
            mag = mag ** self.power
        return Tensor(mag)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        fb = F.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                    htk, norm, dtype)
        self.register_buffer("fbank_matrix", fb)

    def forward(self, x):
        spec = self._spectrogram(x)._value      # [..., freq, time]
        mel = jnp.matmul(self.fbank_matrix._value.astype(spec.dtype),
                         spec)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, self.ref_value, self.amin,
                             self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40,
                 n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        dct = F.create_dct(n_mfcc, n_mels, dtype=dtype)
        self.register_buffer("dct_matrix", dct)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)._value  # [..., mel, time]
        mfcc = jnp.einsum("...mt,mk->...kt", logmel,
                          self.dct_matrix._value.astype(logmel.dtype))
        return Tensor(mfcc)
