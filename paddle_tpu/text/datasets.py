"""Synthetic-backed text datasets + viterbi decode (upstream
python/paddle/text/{datasets,viterbi_decode}).

Each dataset is a map-style ``io.Dataset`` with the upstream field
layout.  Data is generated from a seeded RNG per (mode, size): stable
across runs, no network."""

from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..framework import env_knobs
from ..io.dataset import Dataset
from ..tensor import Tensor


def _n(default=512):
    return int(env_knobs.get_raw("PADDLE_TPU_SYNTH_N", default))


class Imdb(Dataset):
    """Movie-review sentiment: (ids int64 [seq], label int64)."""

    def __init__(self, mode: str = "train", cutoff: int = 150,
                 seq_len: int = 128, vocab_size: int = 5147):
        self.mode = mode
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self._seed = {"train": 1, "test": 2}.get(mode, 3)
        self._n = _n()
        self.word_idx = {f"w{i}": i for i in range(vocab_size)}

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        # per-INDEX seed: same index always returns the same sample
        # (map-style Dataset contract)
        rng = np.random.RandomState(self._seed * 1000003 + i)
        label = np.int64(i % 2)
        # sentiment-correlated token distribution so models can learn
        lo, hi = (0, self.vocab_size // 2) if label == 0 else \
            (self.vocab_size // 2, self.vocab_size)
        ids = rng.randint(lo, hi, self.seq_len).astype(np.int64)
        return ids, label


class Imikolov(Dataset):
    """PTB-style n-gram LM: tuple of n int64 ids."""

    def __init__(self, mode: str = "train", data_type: str = "NGRAM",
                 window_size: int = 5, min_word_freq: int = 50):
        if data_type not in ("NGRAM",):
            raise NotImplementedError(
                f"Imikolov data_type={data_type!r}: only 'NGRAM' is "
                "implemented on this build (SEQ pending)")
        self.window_size = window_size
        self.vocab_size = 2074
        self._n = _n()
        self._seed = {"train": 11, "test": 12}.get(mode, 13)
        self.word_idx = {f"w{i}": i for i in range(self.vocab_size)}

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        rng = np.random.RandomState(self._seed * 1000003 + i)
        return tuple(rng.randint(0, self.vocab_size,
                                 self.window_size).astype(np.int64))


class Movielens(Dataset):
    """Rating prediction: (user_id, gender, age, job, movie_id,
    category, title, rating)."""

    def __init__(self, mode: str = "train", test_ratio: float = 0.1,
                 rand_seed: int = 0):
        self._n = _n()
        self._seed = {"train": 21, "test": 22}.get(mode, 23)

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        rng = np.random.RandomState(self._seed * 1000003 + i)
        return (np.int64(rng.randint(1, 6041)),
                np.int64(rng.randint(0, 2)),
                np.int64(rng.randint(0, 7)),
                np.int64(rng.randint(0, 21)),
                np.int64(rng.randint(1, 3953)),
                rng.randint(0, 19, 3).astype(np.int64),
                rng.randint(0, 5215, 4).astype(np.int64),
                np.float32(rng.randint(1, 6)))


class UCIHousing(Dataset):
    """Boston housing regression: (features f32[13], price f32[1])."""

    def __init__(self, mode: str = "train"):
        self._n = _n(404 if mode == "train" else 102)
        rng = np.random.RandomState(31 if mode == "train" else 32)
        self._x = rng.randn(self._n, 13).astype(np.float32)
        w = np.linspace(-1, 1, 13).astype(np.float32)
        self._y = (self._x @ w + 22.5
                   + rng.randn(self._n).astype(np.float32) * 0.5)

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        return self._x[i], self._y[i:i + 1]


class _WMTBase(Dataset):
    def __init__(self, mode, src_dict_size, trg_dict_size, seq_len=32):
        self._n = _n()
        self._seed = {"train": 41, "test": 42, "dev": 43,
                      "val": 43}.get(mode, 44)
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        self.seq_len = seq_len

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        rng = np.random.RandomState(self._seed * 1000003 + i)
        src = rng.randint(3, self.src_dict_size,
                          self.seq_len).astype(np.int64)
        trg = rng.randint(3, self.trg_dict_size,
                          self.seq_len).astype(np.int64)
        trg_next = np.roll(trg, -1)
        return src, trg, trg_next


class WMT14(_WMTBase):
    def __init__(self, mode: str = "train", dict_size: int = 30000):
        super().__init__(mode, dict_size, dict_size)


class WMT16(_WMTBase):
    def __init__(self, mode: str = "train", src_dict_size: int = 30000,
                 trg_dict_size: int = 30000, lang: str = "en"):
        super().__init__(mode, src_dict_size, trg_dict_size)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True):
    """CRF viterbi decode (parity: paddle.text.viterbi_decode).

    potentials: [B, T, N] emission scores; transition_params: [N, N];
    lengths: [B] int64.  Returns (scores [B], paths [B, T] int64).
    ``include_bos_eos_tag=True`` (upstream default): the LAST TWO tag
    columns are BOS/EOS — start scores come from trans[BOS, :] and
    stop scores from trans[:, EOS], and neither pseudo-tag is emitted
    in the decoded path.  Pure lax.scan — jit/TPU friendly."""
    import jax
    from jax import lax

    pot = potentials._value if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params._value \
        if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    lens = lengths._value if isinstance(lengths, Tensor) \
        else jnp.asarray(lengths)
    B, T, N = pot.shape
    if include_bos_eos_tag:
        if N < 3:
            raise ValueError(
                "include_bos_eos_tag=True needs at least 3 tags "
                "(real tags + BOS + EOS)")
        # upstream convention: the LAST tag is the start (BOS) tag,
        # the second-to-last is the stop (EOS) tag
        bos, eos = N - 1, N - 2
        real = N - 2
        # start: BOS -> tag transition added to the first emission;
        # stop: tag -> EOS added after the last frame.  The pseudo
        # tags never appear in the path: decode over the real tags.
        start = trans[bos, :real]
        stop = trans[:real, eos]
        pot = pot[:, :, :real].at[:, 0, :].add(start[None])
        # add stop score at each sequence's LAST valid frame
        t_idx = jnp.arange(T)[None, :, None]
        last = (lens - 1)[:, None, None]
        pot = pot + jnp.where(t_idx == last, stop[None, None, :], 0.0)
        trans = trans[:real, :real]
        N = real

    def step(carry, t):
        alpha = carry                       # [B, N]
        emit = pot[:, t]                    # [B, N]
        scores = alpha[:, :, None] + trans[None]     # [B, N, N]
        best_prev = jnp.argmax(scores, axis=1)       # [B, N]
        alpha_new = jnp.max(scores, axis=1) + emit
        # frozen past the sequence end
        active = (t < lens)[:, None]
        alpha_new = jnp.where(active, alpha_new, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.broadcast_to(jnp.arange(N)[None],
                                               (B, N)))
        return alpha_new, best_prev

    alpha0 = pot[:, 0]
    alpha, backptrs = lax.scan(step, alpha0, jnp.arange(1, T))
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)            # [B]

    def backtrack(carry, bp_t):
        tag = carry                                  # [B]
        prev = jnp.take_along_axis(bp_t, tag[:, None], 1)[:, 0]
        return prev, tag

    first_tag, path_rev = lax.scan(backtrack, last_tag, backptrs,
                                   reverse=True)
    # reverse scan emits tags 1..T-1 in order; the final carry is tag 0
    paths = jnp.concatenate([first_tag[None], path_rev], 0)
    paths = jnp.transpose(paths, (1, 0))             # [B, T]
    return Tensor(scores), Tensor(paths.astype(jnp.int64))


from ..nn import Layer as _Layer


class ViterbiDecoder(_Layer):
    """nn.Layer wrapper (upstream paddle.text.ViterbiDecoder): the
    transitions register as a buffer so state_dict / sublayer walks /
    dtype moves see them."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        t = transitions if isinstance(transitions, Tensor) \
            else Tensor(np.asarray(transitions))
        self.register_buffer("transitions", t)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
