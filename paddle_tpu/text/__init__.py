"""paddle.text (parity: python/paddle/text/datasets/) — the core-paddle
text datasets, backed by deterministic synthetic corpora (this build is
offline; the real downloads are unavailable, same policy as
paddle_tpu.vision.datasets).  Shapes/dtypes/field layouts match
upstream so input pipelines port unchanged; set PADDLE_TPU_SYNTH_N to
resize."""

from .datasets import (  # noqa
    Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, ViterbiDecoder,
    viterbi_decode)
