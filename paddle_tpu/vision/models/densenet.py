"""DenseNet (parity: python/paddle/vision/models/densenet.py):
dense blocks with concatenative feature reuse + transition layers."""

from __future__ import annotations

from ... import nn

_CFG = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
        169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
        264: (6, 12, 64, 48)}


class DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        from ... import ops
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return ops.concat([x, out], axis=1)


class Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=None, bn_size=4,
                 dropout=0.0, num_classes=1000, with_pool=True):
        super().__init__()
        if layers == 161:
            # densenet161's canonical config; an EXPLICIT growth_rate
            # is honored (review finding: it was silently overwritten)
            growth_rate = 48 if growth_rate is None else growth_rate
            init_c = 96
        else:
            growth_rate = 32 if growth_rate is None else growth_rate
            init_c = 64
        block_cfg = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(init_c)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        c = init_c
        for i, reps in enumerate(block_cfg):
            for _ in range(reps):
                blocks.append(DenseLayer(c, growth_rate, bn_size,
                                         dropout))
                c += growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(Transition(c, c // 2))
                c = c // 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_final = nn.BatchNorm2D(c)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        from ... import ops
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.relu(self.bn_final(self.blocks(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
