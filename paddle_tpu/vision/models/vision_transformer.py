"""ViT (baseline config 5 names ViT-L/16 — BASELINE.json:11; upstream
lives in PaddleClas, the layer set is core paddle.nn).

Pure transformer on patches: all matmul/attention — the best-case
MXU workload.  Attention uses flash_attention for long token counts.
"""

from __future__ import annotations

import numpy as np

from ... import nn, ops
from ...tensor import Tensor


class PatchEmbed(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 embed_dim=768):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = nn.Conv2D(in_chans, embed_dim, patch_size,
                              stride=patch_size)

    def forward(self, x):
        x = self.proj(x)                     # B, E, H/P, W/P
        x = ops.flatten(x, 2)                # B, E, N
        return ops.transpose(x, [0, 2, 1])   # B, N, E


class MLP(nn.Layer):
    def __init__(self, dim, hidden, drop=0.0):
        super().__init__()
        self.fc1 = nn.Linear(dim, hidden)
        self.act = nn.GELU()
        self.fc2 = nn.Linear(hidden, dim)
        self.drop = nn.Dropout(drop)

    def forward(self, x):
        return self.drop(self.fc2(self.drop(self.act(self.fc1(x)))))


class Attention(nn.Layer):
    def __init__(self, dim, num_heads, qkv_bias=True, attn_drop=0.0,
                 proj_drop=0.0):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.qkv = nn.Linear(dim, dim * 3,
                             bias_attr=None if qkv_bias else False)
        self.proj = nn.Linear(dim, dim)
        self.proj_drop = nn.Dropout(proj_drop)

    def forward(self, x):
        b, n, c = x.shape
        qkv = ops.reshape(self.qkv(x), [b, n, 3, self.num_heads,
                                        self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        out = ops.scaled_dot_product_attention(q, k, v)
        out = ops.reshape(out, [b, n, c])
        return self.proj_drop(self.proj(out))


class Block(nn.Layer):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, qkv_bias=True,
                 drop=0.0, attn_drop=0.0, epsilon=1e-6):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, epsilon=epsilon)
        self.attn = Attention(dim, num_heads, qkv_bias, attn_drop, drop)
        self.norm2 = nn.LayerNorm(dim, epsilon=epsilon)
        self.mlp = MLP(dim, int(dim * mlp_ratio), drop)

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        x = x + self.mlp(self.norm2(x))
        return x


class VisionTransformer(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 num_classes=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, qkv_bias=True, drop_rate=0.0,
                 attn_drop_rate=0.0, epsilon=1e-6, **kwargs):
        super().__init__()
        self.num_classes = num_classes
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans,
                                      embed_dim)
        num_patches = self.patch_embed.num_patches
        self.cls_token = self.create_parameter(
            shape=[1, 1, embed_dim],
            default_initializer=nn.initializer.TruncatedNormal(std=0.02))
        self.pos_embed = self.create_parameter(
            shape=[1, num_patches + 1, embed_dim],
            default_initializer=nn.initializer.TruncatedNormal(std=0.02))
        self.pos_drop = nn.Dropout(drop_rate)
        self.blocks = nn.LayerList([
            Block(embed_dim, num_heads, mlp_ratio, qkv_bias, drop_rate,
                  attn_drop_rate, epsilon) for _ in range(depth)])
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)
        if num_classes > 0:
            self.head = nn.Linear(embed_dim, num_classes)

    def _pos_embed_for(self, n_patches: int):
        """Position embeddings for an ``n_patches`` input — bilinear
        grid interpolation when the resolution differs from the build
        size (the standard ViT multi-resolution recipe; PaddleClas
        resize_pos_embed parity).  ``n_patches`` is a static Python int
        per compiled bucket, so each bucket compiles its own resized
        table — config 5's bucketed dynamic-shape strategy (SURVEY.md
        §7.3 hard part 3)."""
        n_built = int(self.pos_embed.shape[1]) - 1
        if n_patches == n_built:
            return self.pos_embed
        cls_pe = self.pos_embed[:, :1]
        grid_pe = self.pos_embed[:, 1:]
        g_old = int(round(float(n_built) ** 0.5))
        g_new = int(round(float(n_patches) ** 0.5))
        if g_old * g_old != n_built or g_new * g_new != n_patches:
            raise ValueError(
                f"cannot interpolate position embeddings from "
                f"{n_built} to {n_patches} patches: non-square grid")
        e = grid_pe.shape[2]
        pe = ops.transpose(ops.reshape(grid_pe, [1, g_old, g_old, e]),
                           [0, 3, 1, 2])
        pe = ops.interpolate(pe, size=[g_new, g_new], mode="bilinear",
                             align_corners=False)
        pe = ops.reshape(ops.transpose(pe, [0, 2, 3, 1]),
                         [1, g_new * g_new, e])
        return ops.concat([cls_pe, pe], axis=1)

    def forward(self, x):
        b = x.shape[0]
        x = self.patch_embed(x)
        cls = ops.expand(self.cls_token, [b, 1, self.cls_token.shape[2]])
        pos = self._pos_embed_for(int(x.shape[1]))
        x = ops.concat([cls, x], axis=1)
        x = self.pos_drop(x + pos)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        if self.num_classes > 0:
            return self.head(x[:, 0])
        return x


def vit_b_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=768, depth=12,
                             num_heads=12, **kwargs)


def vit_l_16(pretrained=False, **kwargs):
    return VisionTransformer(patch_size=16, embed_dim=1024, depth=24,
                             num_heads=16, **kwargs)
