"""PP-YOLOE-class anchor-free detector (parity: PaddleDetection
ppdet/modeling/{backbones/cspresnet.py, necks/custom_pan.py,
heads/ppyoloe_head.py, assigners/task_aligned_assigner.py} — the
BASELINE.json config-5 detector family; SURVEY.md §2.2 paddle.vision).

TPU-first design decisions (vs the CUDA reference):

- **Everything is dense and statically shaped.**  The reference's
  assigner gathers variable-length positive lists per image; here the
  task-aligned assignment is a [B, A, G] mask computation (booleans +
  where), so the whole train step — backbone, neck, head, assignment,
  loss — compiles into ONE XLA program with no host sync.  Variable
  image sizes come from the bucketed loader (io/bucketing.py): one
  compiled program per bucket, padded gt boxes carried with a validity
  mask.
- **DFL regression** (distribution over reg_max+1 bins) is a pair of
  matmul-shaped ops — MXU-friendly — instead of the reference's custom
  CUDA kernels.
- NMS runs only in eval via the masked fixed-iteration kernels in
  vision/ops.py (multiclass_nms).
"""

from __future__ import annotations

import math
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor import Tensor
from ... import nn, ops
from ...nn import Layer
from .. import ops as vops


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class ConvBNAct(Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return nn.functional.silu(x) if self.act else x


class ESEAttn(Layer):
    """Effective squeeze-excitation (cspresnet.py EffectiveSELayer)."""

    def __init__(self, ch):
        super().__init__()
        self.fc = nn.Conv2D(ch, ch, 1)

    def forward(self, feat, avg_feat=None):
        if avg_feat is None:
            avg_feat = nn.functional.adaptive_avg_pool2d(feat, 1)
        w = ops.sigmoid(self.fc(avg_feat))
        return feat * w


class BasicBlock(Layer):
    def __init__(self, ch, shortcut=True):
        super().__init__()
        self.conv1 = ConvBNAct(ch, ch, 3)
        self.conv2 = ConvBNAct(ch, ch, 3)
        self.shortcut = shortcut

    def forward(self, x):
        y = self.conv2(self.conv1(x))
        return x + y if self.shortcut else y


class CSPResStage(Layer):
    """CSP stage: downsample, split 1x1, residual tower, fuse."""

    def __init__(self, cin, cout, n):
        super().__init__()
        self.down = ConvBNAct(cin, cout, 3, stride=2)
        mid = cout // 2
        self.conv1 = ConvBNAct(cout, mid, 1)
        self.conv2 = ConvBNAct(cout, mid, 1)
        self.blocks = nn.Sequential(*[BasicBlock(mid) for _ in range(n)])
        self.attn = ESEAttn(mid * 2)
        self.conv3 = ConvBNAct(mid * 2, cout, 1)

    def forward(self, x):
        x = self.down(x)
        y1 = self.conv1(x)
        y2 = self.blocks(self.conv2(x))
        y = ops.concat([y1, y2], axis=1)
        return self.conv3(self.attn(y))


class CSPResNet(Layer):
    """cspresnet.py backbone, lite: stem + 3 CSP stages → (C3, C4, C5)
    at strides 8/16/32."""

    def __init__(self, width=(32, 64, 128, 256), depth=(1, 1, 1)):
        super().__init__()
        self.stem = nn.Sequential(
            ConvBNAct(3, width[0] // 2, 3, stride=2),
            ConvBNAct(width[0] // 2, width[0], 3, stride=2))
        self.stages = nn.LayerList([
            CSPResStage(width[i], width[i + 1], depth[i])
            for i in range(3)])
        self.out_channels = list(width[1:])

    def forward(self, x):
        x = self.stem(x)
        outs = []
        for st in self.stages:
            x = st(x)
            outs.append(x)
        return outs  # strides 8, 16, 32


class CSPPAN(Layer):
    """custom_pan.py: top-down FPN + bottom-up PAN with CSP fuse
    blocks; channel-matched 1x1 laterals."""

    def __init__(self, in_channels: Sequence[int], out_ch=96):
        super().__init__()
        n = len(in_channels)
        self.lateral = nn.LayerList(
            [ConvBNAct(c, out_ch, 1) for c in in_channels])
        self.td_blocks = nn.LayerList(
            [ConvBNAct(out_ch * 2, out_ch, 3) for _ in range(n - 1)])
        self.down = nn.LayerList(
            [ConvBNAct(out_ch, out_ch, 3, stride=2)
             for _ in range(n - 1)])
        self.bu_blocks = nn.LayerList(
            [ConvBNAct(out_ch * 2, out_ch, 3) for _ in range(n - 1)])
        self.out_channels = [out_ch] * n

    def forward(self, feats):
        lat = [l(f) for l, f in zip(self.lateral, feats)]
        # top-down
        td = [None] * len(lat)
        td[-1] = lat[-1]
        for i in range(len(lat) - 2, -1, -1):
            up = nn.functional.interpolate(td[i + 1], scale_factor=2,
                                           mode="nearest")
            td[i] = self.td_blocks[i](
                ops.concat([lat[i], up], axis=1))
        # bottom-up
        out = [td[0]]
        for i in range(len(lat) - 1):
            d = self.down[i](out[-1])
            out.append(self.bu_blocks[i](
                ops.concat([td[i + 1], d], axis=1)))
        return out


# ---------------------------------------------------------------------------
# head + losses (pure jnp below the Layer surface)
# ---------------------------------------------------------------------------

def _make_anchors(feat_shapes, strides, offset=0.5):
    """Cell-center anchor points [A, 2] (xy, image coords) + stride[A]."""
    pts, sts = [], []
    for (h, w), s in zip(feat_shapes, strides):
        xs = (jnp.arange(w, dtype=jnp.float32) + offset) * s
        ys = (jnp.arange(h, dtype=jnp.float32) + offset) * s
        gx, gy = jnp.meshgrid(xs, ys)
        pts.append(jnp.stack([gx.reshape(-1), gy.reshape(-1)], -1))
        sts.append(jnp.full((h * w,), float(s), jnp.float32))
    return jnp.concatenate(pts, 0), jnp.concatenate(sts, 0)


def _dist2bbox(points, ltrb):
    """(l, t, r, b) distances → xyxy boxes."""
    x, y = points[..., 0], points[..., 1]
    l, t, r, b = (ltrb[..., 0], ltrb[..., 1], ltrb[..., 2], ltrb[..., 3])
    return jnp.stack([x - l, y - t, x + r, y + b], -1)


def _bbox2dist(points, boxes, reg_max):
    x, y = points[..., 0], points[..., 1]
    l = x - boxes[..., 0]
    t = y - boxes[..., 1]
    r = boxes[..., 2] - x
    b = boxes[..., 3] - y
    return jnp.clip(jnp.stack([l, t, r, b], -1), 0, reg_max - 0.01)


def _pairwise_iou(a, b, eps=1e-9):
    """a: [..., A, 4], b: [..., G, 4] → [..., A, G]."""
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = ((a[..., 2] - a[..., 0]) *
              (a[..., 3] - a[..., 1]))[..., :, None]
    area_b = ((b[..., 2] - b[..., 0]) *
              (b[..., 3] - b[..., 1]))[..., None, :]
    return inter / (area_a + area_b - inter + eps)


def _giou(a, b, eps=1e-9):
    """Elementwise GIoU, a/b: [..., 4]."""
    lt = jnp.maximum(a[..., :2], b[..., :2])
    rb = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    union = area_a + area_b - inter
    iou = inter / (union + eps)
    clt = jnp.minimum(a[..., :2], b[..., :2])
    crb = jnp.maximum(a[..., 2:], b[..., 2:])
    cwh = jnp.clip(crb - clt, 0)
    carea = cwh[..., 0] * cwh[..., 1]
    return iou - (carea - union) / (carea + eps)


def task_aligned_assign(scores, pred_boxes, points, gt_boxes, gt_labels,
                        gt_mask, topk=9, alpha=1.0, beta=6.0, eps=1e-9):
    """TAL (task_aligned_assigner.py), fully dense.

    scores: [B, A, C] (sigmoid cls), pred_boxes: [B, A, 4],
    points: [A, 2], gt_boxes: [B, G, 4], gt_labels: [B, G] int,
    gt_mask: [B, G] (1 = real box).
    Returns: pos_mask [B, A], assigned_gt [B, A] int, assigned_score
    [B, A] (normalized alignment for the cls target).
    """
    B, A, C = scores.shape
    G = gt_boxes.shape[1]
    ious = _pairwise_iou(pred_boxes, gt_boxes)              # [B, A, G]
    cls_of_gt = jnp.take_along_axis(
        scores, jnp.clip(gt_labels, 0, C - 1)[:, None, :].repeat(A, 1),
        axis=2)                                             # [B, A, G]
    # anchor center inside gt box
    px = points[None, :, None, 0]
    py = points[None, :, None, 1]
    inside = ((px >= gt_boxes[:, None, :, 0]) &
              (py >= gt_boxes[:, None, :, 1]) &
              (px <= gt_boxes[:, None, :, 2]) &
              (py <= gt_boxes[:, None, :, 3]))              # [B, A, G]
    valid = inside & (gt_mask[:, None, :] > 0)
    metric = (cls_of_gt ** alpha) * (ious ** beta)
    metric = jnp.where(valid, metric, 0.0)
    # top-k anchors per gt by metric
    k = min(topk, A)
    topv, _ = jax.lax.top_k(metric.transpose(0, 2, 1), k)   # [B, G, k]
    thresh = topv[..., -1:].transpose(0, 2, 1)              # [B, 1, G]
    is_topk = (metric >= jnp.maximum(thresh, eps)) & valid  # [B, A, G]
    # conflict resolution: anchor claimed by several gts → max-IoU gt
    assign_metric = jnp.where(is_topk, ious, -1.0)
    assigned_gt = jnp.argmax(assign_metric, axis=-1)        # [B, A]
    pos_mask = jnp.any(is_topk, axis=-1)                    # [B, A]
    # normalized alignment target (ppyoloe: t_hat = t / max_t * max_iou)
    chosen = jnp.take_along_axis(
        metric, assigned_gt[..., None], -1)[..., 0]
    chosen_iou = jnp.take_along_axis(
        ious, assigned_gt[..., None], -1)[..., 0]
    per_gt_max_metric = jnp.max(metric, axis=1, keepdims=True)  # [B,1,G]
    per_gt_max_iou = jnp.max(jnp.where(is_topk, ious, 0.0), axis=1,
                             keepdims=True)
    max_m = jnp.take_along_axis(
        per_gt_max_metric[:, 0], assigned_gt, -1)
    max_i = jnp.take_along_axis(per_gt_max_iou[:, 0], assigned_gt, -1)
    assigned_score = chosen / (max_m + eps) * max_i
    assigned_score = jnp.where(pos_mask, assigned_score, 0.0)
    return pos_mask, assigned_gt, assigned_score, chosen_iou


class PPYOLOEHead(Layer):
    """Decoupled anchor-free head with DFL regression
    (ppyoloe_head.py): per-level ESE stems, cls branch, reg branch
    over reg_max+1 bins; losses = varifocal-style BCE + GIoU + DFL."""

    def __init__(self, in_channels: Sequence[int], num_classes=80,
                 strides=(8, 16, 32), reg_max=8):
        super().__init__()
        assert len(set(in_channels)) == 1, "PAN emits equal channels"
        ch = in_channels[0]
        self.num_classes = num_classes
        self.strides = list(strides)
        self.reg_max = reg_max
        self.stem_cls = nn.LayerList(
            [ESEAttn(ch) for _ in strides])
        self.stem_reg = nn.LayerList(
            [ESEAttn(ch) for _ in strides])
        self.pred_cls = nn.LayerList(
            [nn.Conv2D(ch, num_classes, 3, padding=1)
             for _ in strides])
        self.pred_reg = nn.LayerList(
            [nn.Conv2D(ch, 4 * (reg_max + 1), 3, padding=1)
             for _ in strides])
        # bias init: prior prob 0.01 (focal-loss style stable start)
        b = -math.log((1 - 0.01) / 0.01)
        for conv in self.pred_cls:
            conv.bias._value = jnp.full_like(conv.bias._value, b)

    def _raw(self, feats):
        """Per-level raw maps → flattened [B, A, C] / [B, A, 4*(R+1)],
        plus static feature shapes."""
        cls_list, reg_list, shapes = [], [], []
        for i, f in enumerate(feats):
            v = _v(f)
            B, _, H, W = v.shape
            c = _v(self.pred_cls[i](self.stem_cls[i](f)))
            r = _v(self.pred_reg[i](self.stem_reg[i](f)))
            cls_list.append(c.reshape(B, self.num_classes, H * W)
                            .transpose(0, 2, 1))
            reg_list.append(r.reshape(B, 4 * (self.reg_max + 1), H * W)
                            .transpose(0, 2, 1))
            shapes.append((H, W))
        return (jnp.concatenate(cls_list, 1),
                jnp.concatenate(reg_list, 1), shapes)

    def _decode(self, reg, points, stride):
        """DFL expectation → ltrb (stride units) → xyxy image coords."""
        B, A, _ = reg.shape
        R = self.reg_max + 1
        logits = reg.reshape(B, A, 4, R)
        dist = (jax.nn.softmax(logits, -1) *
                jnp.arange(R, dtype=jnp.float32)).sum(-1)
        return _dist2bbox(points[None], dist * stride[None, :, None]), \
            logits

    def forward(self, feats):
        cls, reg, shapes = self._raw(feats)
        points, stride = _make_anchors(shapes, self.strides)
        boxes, _ = self._decode(reg, points, stride)
        return Tensor(jax.nn.sigmoid(cls)), Tensor(boxes)

    def loss(self, feats, gt_boxes, gt_labels, gt_mask,
             cls_weight=1.0, iou_weight=2.5, dfl_weight=0.5):
        """Train losses.  The conv towers run through the taped layer
        stack; the pure-jnp assignment+loss math is recorded as ONE
        tape node via apply_closure, so eager ``loss.backward()``
        differentiates straight through it (and under jit it is
        ordinary traced code)."""
        raw_maps = []
        shapes = []
        for i, f in enumerate(feats):
            c = self.pred_cls[i](self.stem_cls[i](f))     # taped
            r = self.pred_reg[i](self.stem_reg[i](f))     # taped
            raw_maps += [c, r]
            shapes.append((c.shape[2], c.shape[3]))
        gtb = _v(gt_boxes)
        gtl = _v(gt_labels).astype(jnp.int32)
        gtm = _v(gt_mask)

        def closure(*maps):
            return self._loss_math(maps, shapes, gtb, gtl, gtm,
                                   cls_weight, iou_weight, dfl_weight)

        from ...ops._primitive import apply_closure
        total, cls_l, iou_l, dfl_l = apply_closure(
            closure, raw_maps, name="ppyoloe_loss")
        return {"loss": total, "loss_cls": cls_l,
                "loss_iou": iou_l, "loss_dfl": dfl_l}

    def _loss_math(self, maps, shapes, gt_boxes, gt_labels, gt_mask,
                   cls_weight, iou_weight, dfl_weight):
        """Pure jnp: maps are the per-level (cls, reg) conv outputs."""
        cls_list, reg_list = [], []
        for i, (H, W) in enumerate(shapes):
            c, r = maps[2 * i], maps[2 * i + 1]
            B = c.shape[0]
            cls_list.append(c.reshape(B, self.num_classes, H * W)
                            .transpose(0, 2, 1))
            reg_list.append(r.reshape(B, 4 * (self.reg_max + 1), H * W)
                            .transpose(0, 2, 1))
        cls = jnp.concatenate(cls_list, 1)
        reg = jnp.concatenate(reg_list, 1)
        points, stride = _make_anchors(shapes, self.strides)
        pred_boxes, logits = self._decode(reg, points, stride)
        scores = jax.nn.sigmoid(cls)
        pos, agt, ascore, aiou = task_aligned_assign(
            jax.lax.stop_gradient(scores),
            jax.lax.stop_gradient(pred_boxes),
            points, gt_boxes, gt_labels, gt_mask)

        B, A, C = cls.shape
        tgt_label = jnp.take_along_axis(
            gt_labels.astype(jnp.int32), agt, -1)           # [B, A]
        onehot = jax.nn.one_hot(tgt_label, C)
        cls_target = onehot * ascore[..., None]
        # varifocal-style weighting: positives by target quality,
        # negatives by p^2 (focal down-weight of easy background)
        w = jnp.where(pos[..., None], cls_target,
                      0.75 * scores ** 2.0)
        bce = -(cls_target * jax.nn.log_sigmoid(cls) +
                (1 - cls_target) * jax.nn.log_sigmoid(-cls))
        denom = jnp.maximum(ascore.sum(), 1.0)
        cls_loss = (w * bce).sum() / denom

        tgt_box = jnp.take_along_axis(
            gt_boxes, agt[..., None].repeat(4, -1), 1)      # [B, A, 4]
        wbox = (ascore * pos)[..., None]
        giou_loss = ((1.0 - _giou(pred_boxes, tgt_box)) *
                     wbox[..., 0]).sum() / denom

        # DFL: CE against the two integer bins bracketing the target
        # distance measured in stride units
        tdist = _bbox2dist(points[None], tgt_box, 1e9) / \
            stride[None, :, None]
        tdist = jnp.clip(tdist, 0, self.reg_max - 0.01)
        tl = jnp.floor(tdist)
        wr = tdist - tl
        wl = 1.0 - wr
        logp = jax.nn.log_softmax(logits, -1)               # [B,A,4,R]
        pl = jnp.take_along_axis(
            logp, tl.astype(jnp.int32)[..., None], -1)[..., 0]
        pr = jnp.take_along_axis(
            logp, (tl + 1).astype(jnp.int32)[..., None], -1)[..., 0]
        dfl = -(wl * pl + wr * pr).mean(-1)                 # [B, A]
        dfl_loss = (dfl * wbox[..., 0]).sum() / denom

        total = (cls_weight * cls_loss + iou_weight * giou_loss +
                 dfl_weight * dfl_loss)
        return total, cls_loss, giou_loss, dfl_loss


class PPYOLOE(Layer):
    """Assembled detector: CSPResNet + CSPPAN + PPYOLOEHead.

    Train: ``model(images, gt_boxes=..., gt_labels=..., gt_mask=...)``
    → loss dict.  Eval: ``model(images)`` → (scores [B, A, C],
    boxes [B, A, 4]); ``postprocess`` applies multiclass NMS."""

    def __init__(self, num_classes=80, width=(32, 64, 128, 256),
                 depth=(1, 1, 1), neck_ch=96, reg_max=8):
        super().__init__()
        self.backbone = CSPResNet(width, depth)
        self.neck = CSPPAN(self.backbone.out_channels, neck_ch)
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes,
                                reg_max=reg_max)

    def forward(self, images, gt_boxes=None, gt_labels=None,
                gt_mask=None):
        feats = self.neck(self.backbone(images))
        if gt_boxes is not None:
            return self.head.loss(feats, gt_boxes, gt_labels, gt_mask)
        return self.head(feats)

    def postprocess(self, scores, boxes, score_threshold=0.05,
                    nms_threshold=0.6, keep_top_k=100):
        """Per-image multiclass NMS → (out [N, 6] (label, score,
        x1, y1, x2, y2), counts)."""
        sv, bv = _v(scores), _v(boxes)
        outs = []
        for b in range(sv.shape[0]):
            outs.append(vops.multiclass_nms(
                Tensor(bv[b]), Tensor(sv[b].T),
                score_threshold=score_threshold,
                nms_threshold=nms_threshold, keep_top_k=keep_top_k))
        return outs


def ppyoloe_crn_s(num_classes=80, **kw):
    """PP-YOLOE-s-class config (scaled CSPResNet widths)."""
    return PPYOLOE(num_classes, width=(32, 64, 128, 256),
                   depth=(1, 2, 2), neck_ch=96, **kw)


def ppyoloe_tiny(num_classes=20, **kw):
    """Test-scale config: same topology, minimal channels."""
    return PPYOLOE(num_classes, width=(16, 32, 48, 64),
                   depth=(1, 1, 1), neck_ch=32, reg_max=8, **kw)
