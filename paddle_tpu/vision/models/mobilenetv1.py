"""MobileNetV1 (parity: python/paddle/vision/models/mobilenetv1.py):
depthwise-separable conv stack — depthwise convs map to XLA grouped
convolutions (feature_group_count = channels)."""

from __future__ import annotations

from ... import nn
from ._utils import ConvNormAct


class ConvBNLayer(ConvNormAct):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0,
                 groups=1):
        super().__init__(in_c, out_c, kernel, stride=stride,
                         padding=padding, groups=groups, act="relu")


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, stride, scale):
        super().__init__()
        self.dw = ConvBNLayer(int(in_c * scale), int(mid_c * scale), 3,
                              stride=stride, padding=1,
                              groups=int(in_c * scale))
        self.pw = ConvBNLayer(int(mid_c * scale), int(out_c * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2,
                                 padding=1)
        cfg = [  # in, mid, out, stride
            (32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
            (128, 128, 256, 2), (256, 256, 256, 1),
            (256, 256, 512, 2),
            (512, 512, 512, 1), (512, 512, 512, 1),
            (512, 512, 512, 1), (512, 512, 512, 1),
            (512, 512, 512, 1),
            (512, 512, 1024, 2), (1024, 1024, 1024, 1)]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, m, o, s, scale) for i, m, o, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        from ... import ops
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline")
    return MobileNetV1(scale=scale, **kwargs)
