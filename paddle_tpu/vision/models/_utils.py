"""Shared building blocks for the vision model zoo."""

from __future__ import annotations

from ... import nn


def make_divisible(v, divisor=8, min_value=None):
    """Round channel counts to hardware-friendly multiples (the
    MobileNet paper rule, shared by v2/v3/shufflenet)."""
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


_ACTS = {
    None: nn.Identity,
    "relu": nn.ReLU,
    "relu6": nn.ReLU6,
    "hardswish": nn.Hardswish,
    "swish": nn.Swish,
}


class ConvNormAct(nn.Layer):
    """Conv2D + BatchNorm + activation — the block every zoo family
    re-implemented privately; one definition, parameterised."""

    def __init__(self, in_c, out_c, k, stride=1, padding=None, groups=1,
                 act="relu"):
        super().__init__()
        if padding is None:
            padding = (k - 1) // 2 if isinstance(k, int) else \
                tuple((kk - 1) // 2 for kk in k)
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = _ACTS[act]()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))
