from .lenet import LeNet  # noqa
from .resnet import (  # noqa
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152)
from .vgg import VGG, vgg16, vgg19  # noqa
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa
from .vision_transformer import (  # noqa
    VisionTransformer, vit_b_16, vit_l_16)
from .alexnet import AlexNet, alexnet  # noqa
