"""MobileNetV3 small/large (parity: python/paddle/vision/models/
mobilenetv3.py): inverted residuals with squeeze-excite and
hardswish."""

from __future__ import annotations

from ... import nn
from ._utils import ConvNormAct as ConvBNAct
from ._utils import make_divisible as _make_divisible


class SqueezeExcite(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        mid = _make_divisible(channels // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, mid, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(mid, channels, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if mid_c != in_c:
            layers.append(ConvBNAct(in_c, mid_c, 1, act=act))
        layers.append(ConvBNAct(mid_c, mid_c, k, stride=stride,
                                groups=mid_c, act=act))
        if use_se:
            layers.append(SqueezeExcite(mid_c))
        layers.append(ConvBNAct(mid_c, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, use_se, act, stride)
_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1)]
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1)]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        self.conv1 = ConvBNAct(3, in_c, 3, stride=2, act="hardswish")
        blocks = []
        for k, exp, out, se, act, s in config:
            mid = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(InvertedResidual(in_c, mid, out_c, k, s, se,
                                           act))
            in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        last_conv = _make_divisible(6 * in_c)
        # classifier hidden width scales with the model (upstream
        # parity: make_divisible(last_channel * scale))
        last_channel = _make_divisible(last_channel * scale)
        self.conv2 = ConvBNAct(in_c, last_conv, 1, act="hardswish")
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        from ... import ops
        x = self.conv2(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline")
    return MobileNetV3Small(scale=scale, **kwargs)
