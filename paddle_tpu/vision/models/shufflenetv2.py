"""ShuffleNetV2 (parity: python/paddle/vision/models/shufflenetv2.py):
channel-split units with channel shuffle (ops.channel_shuffle)."""

from __future__ import annotations

from ... import nn
from ._utils import ConvNormAct as ConvBN


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                ConvBN(branch, branch, 1, act=act),
                ConvBN(branch, branch, 3, stride=1, groups=branch,
                       act=None),
                ConvBN(branch, branch, 1, act=act))
        else:
            self.branch1 = nn.Sequential(
                ConvBN(in_c, in_c, 3, stride=stride, groups=in_c,
                       act=None),
                ConvBN(in_c, branch, 1, act=act))
            self.branch2 = nn.Sequential(
                ConvBN(in_c, branch, 1, act=act),
                ConvBN(branch, branch, 3, stride=stride, groups=branch,
                       act=None),
                ConvBN(branch, branch, 1, act=act))

    def forward(self, x):
        from ... import ops
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = ops.split(x, [half, half], axis=1)
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return ops.channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512), 0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024), 1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024), 2.0: (24, 244, 488, 976, 2048)}
_REPEATS = (4, 8, 4)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if float(scale) not in _STAGE_OUT:
            raise NotImplementedError(
                f"ShuffleNetV2 scale {scale} unsupported; choose from "
                f"{sorted(_STAGE_OUT)}")
        c0, c1, c2, c3, c_last = _STAGE_OUT[float(scale)]
        self.conv1 = ConvBN(3, c0, 3, stride=2, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = c0
        for out_c, reps in zip((c1, c2, c3), _REPEATS):
            units = [InvertedResidual(in_c, out_c, 2, act)]
            for _ in range(reps - 1):
                units.append(InvertedResidual(out_c, out_c, 1, act))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stage2, self.stage3, self.stage4 = stages
        self.conv5 = ConvBN(in_c, c_last, 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c_last, num_classes)

    def forward(self, x):
        from ... import ops
        x = self.maxpool(self.conv1(x))
        x = self.stage4(self.stage3(self.stage2(x)))
        x = self.conv5(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", pretrained=pretrained,
                       **kwargs)
