"""Detection AP evaluation (host-side numpy).

Parity: upstream PaddleDetection `ppdet/metrics/map_utils.py`
(prune_zero_padding / DetectionMAP) and the fluid-era
`paddle.metric.DetectionMAP` — mAP over classes at a fixed IoU
threshold with VOC-style interpolation.  Evaluation is a host-side
metric in upstream too (it runs between epochs, not inside the
compiled step), so plain numpy is the TPU-native choice as well: the
device path ends at `multiclass_nms` outputs.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

__all__ = ["voc_ap", "eval_detections_ap"]


def _iou_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """IoU between [N,4] and [M,4] xyxy boxes."""
    if len(a) == 0 or len(b) == 0:
        return np.zeros((len(a), len(b)), np.float32)
    ax1, ay1, ax2, ay2 = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix1 = np.maximum(ax1, bx1)
    iy1 = np.maximum(ay1, by1)
    ix2 = np.minimum(ax2, bx2)
    iy2 = np.minimum(ay2, by2)
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    area_a = np.clip(ax2 - ax1, 0, None) * np.clip(ay2 - ay1, 0, None)
    area_b = np.clip(bx2 - bx1, 0, None) * np.clip(by2 - by1, 0, None)
    union = area_a + area_b - inter
    return np.where(union > 0, inter / union, 0.0).astype(np.float32)


def voc_ap(recall: np.ndarray, precision: np.ndarray) -> float:
    """Continuous-interpolation VOC AP (area under the max-envelope
    precision-recall curve; upstream map_type='integral')."""
    r = np.concatenate([[0.0], recall, [1.0]])
    p = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(p) - 2, -1, -1):
        p[i] = max(p[i], p[i + 1])
    idx = np.where(r[1:] != r[:-1])[0]
    return float(np.sum((r[idx + 1] - r[idx]) * p[idx + 1]))


def eval_detections_ap(
        detections: Sequence[np.ndarray],
        gt_boxes: Sequence[np.ndarray],
        gt_labels: Sequence[np.ndarray],
        num_classes: int,
        iou_threshold: float = 0.5) -> Dict[str, object]:
    """AP per class + mAP at one IoU threshold.

    detections: per image, [N, 6] rows (label, score, x1, y1, x2, y2)
      — exactly `multiclass_nms` / `PPYOLOE.postprocess` output;
    gt_boxes / gt_labels: per image, [M, 4] xyxy and [M] int labels
      (pass only valid rows — pruned padding, upstream
      prune_zero_padding).
    """
    aps: Dict[int, float] = {}
    for c in range(num_classes):
        scored: List[Tuple[float, int, int]] = []  # score, img, det idx
        npos = 0
        per_img_gt = []
        for i, (gb, gl) in enumerate(zip(gt_boxes, gt_labels)):
            keep = np.asarray(gl) == c
            per_img_gt.append(np.asarray(gb)[keep])
            npos += int(keep.sum())
        if npos == 0:
            continue
        for i, det in enumerate(detections):
            det = np.asarray(det)
            if det.size == 0:
                continue
            for j in np.where(det[:, 0].astype(int) == c)[0]:
                scored.append((float(det[j, 1]), i, int(j)))
        if not scored:
            aps[c] = 0.0
            continue
        scored.sort(key=lambda t: -t[0])
        matched = [np.zeros(len(g), bool) for g in per_img_gt]
        tp = np.zeros(len(scored))
        fp = np.zeros(len(scored))
        for k, (_s, i, j) in enumerate(scored):
            box = np.asarray(detections[i])[j, 2:6][None, :]
            ious = _iou_matrix(box, per_img_gt[i])[0]
            best = int(np.argmax(ious)) if len(ious) else -1
            if best >= 0 and ious[best] >= iou_threshold \
                    and not matched[i][best]:
                matched[i][best] = True
                tp[k] = 1
            else:
                fp[k] = 1
        ctp, cfp = np.cumsum(tp), np.cumsum(fp)
        rec = ctp / npos
        prec = ctp / np.maximum(ctp + cfp, 1e-9)
        aps[c] = voc_ap(rec, prec)
    mean_ap = float(np.mean(list(aps.values()))) if aps else 0.0
    return {"map": mean_ap, "ap_per_class": aps,
            "iou_threshold": iou_threshold}
