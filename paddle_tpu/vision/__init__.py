"""paddle.vision parity (python/paddle/vision/)."""

from . import models  # noqa
from . import datasets  # noqa
from . import transforms  # noqa
from . import ops  # noqa
from .models import LeNet, ResNet, resnet18, resnet50  # noqa
