"""vision datasets (parity: python/paddle/vision/datasets/).

No network in this environment: MNIST/Cifar load from local files when
present (same file formats as upstream) and fall back to deterministic
synthetic data so the training loops/tests run anywhere.
"""

from .mnist import MNIST, FashionMNIST  # noqa
from .cifar import Cifar10, Cifar100  # noqa
from .synthetic import SyntheticImages  # noqa
