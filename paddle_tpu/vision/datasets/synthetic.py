"""Synthetic image dataset for benchmarking (stands in for ImageNet in
config 2 where no data is mounted)."""

from __future__ import annotations

import numpy as np

from ...io.dataset import Dataset


class SyntheticImages(Dataset):
    def __init__(self, num_samples=1280, image_shape=(3, 224, 224),
                 num_classes=1000, seed=0, dtype=np.float32):
        self.n = num_samples
        self.shape = tuple(image_shape)
        self.num_classes = num_classes
        rng = np.random.RandomState(seed)
        # one shared buffer + per-index shift: O(1) memory
        self._base = rng.rand(*self.shape).astype(dtype)
        self._labels = rng.randint(0, num_classes, size=num_samples
                                   ).astype(np.int64)

    def __getitem__(self, idx):
        img = np.roll(self._base, idx % 16, axis=-1)
        return img, np.asarray([self._labels[idx]], dtype=np.int64)

    def __len__(self):
        return self.n
