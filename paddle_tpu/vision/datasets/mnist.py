"""MNIST (parity: python/paddle/vision/datasets/mnist.py — reads the
idx-ubyte files; offline fallback = deterministic synthetic digits)."""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ...framework import env_knobs
from ...io.dataset import Dataset


def _load_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)


def _load_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8)


def _synthetic_mnist(n, seed):
    """Deterministic class-separable synthetic digits: class k = blob at a
    k-dependent position — learnable by LeNet, so loss-goes-down tests
    are meaningful."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    images = rng.rand(n, 28, 28).astype(np.float32) * 0.15
    ys = (labels % 5) * 5 + 2
    xs = (labels // 5) * 12 + 6
    for i in range(n):
        y, x = ys[i], xs[i]
        images[i, y:y + 6, x:x + 6] += 0.8
    return np.clip(images, 0, 1), labels


class MNIST(Dataset):
    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = True,
                 backend: str = "cv2"):
        self.mode = mode
        self.transform = transform
        root = os.environ.get("PADDLE_DATASET_HOME",
                              os.path.expanduser("~/.cache/paddle/dataset"))
        base = os.path.join(root, self.NAME)
        split = "train" if mode == "train" else "t10k"
        img = image_path or os.path.join(
            base, f"{split}-images-idx3-ubyte.gz")
        lbl = label_path or os.path.join(
            base, f"{split}-labels-idx1-ubyte.gz")
        if os.path.exists(img) and os.path.exists(lbl):
            self.images = (_load_idx_images(img).astype(np.float32) / 255.0)
            self.labels = _load_idx_labels(lbl).astype(np.int64)
        else:
            n = 60000 if mode == "train" else 10000
            n = int(env_knobs.get_raw("PADDLE_TPU_SYNTH_N", n))
            self.images, self.labels = _synthetic_mnist(
                n, seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img = self.images[idx][None, :, :]  # CHW, C=1
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
