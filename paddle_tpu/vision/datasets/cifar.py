"""Cifar10/100 (parity: python/paddle/vision/datasets/cifar.py) with
synthetic fallback."""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Optional

import numpy as np

from ...framework import env_knobs
from ...io.dataset import Dataset


def _synthetic_cifar(n, num_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int64)
    images = rng.rand(n, 3, 32, 32).astype(np.float32) * 0.2
    for i in range(n):
        c = labels[i]
        images[i, c % 3, (c // 3) % 4 * 8:(c // 3) % 4 * 8 + 8] += 0.6
    return np.clip(images, 0, 1), labels


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform=None, download: bool = True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        if data_file and os.path.exists(data_file):
            self._load_archive(data_file)
        else:
            n = 50000 if mode == "train" else 10000
            n = int(env_knobs.get_raw("PADDLE_TPU_SYNTH_N", n))
            self.images, self.labels = _synthetic_cifar(
                n, self.NUM_CLASSES, seed=0 if mode == "train" else 1)

    def _load_archive(self, path):
        images, labels = [], []
        with tarfile.open(path) as tf:
            names = [m for m in tf.getmembers()
                     if ("data_batch" in m.name if self.mode == "train"
                         else "test_batch" in m.name)]
            for m in sorted(names, key=lambda m: m.name):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                images.append(d[b"data"].reshape(-1, 3, 32, 32))
                key = b"labels" if b"labels" in d else b"fine_labels"
                labels.extend(d[key])
        self.images = (np.concatenate(images).astype(np.float32) / 255.0)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    NUM_CLASSES = 100
