"""vision transforms (parity: python/paddle/vision/transforms/) —
numpy-based, CHW float arrays."""

from __future__ import annotations

import numbers
from typing import List, Sequence

import numpy as np


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        c = arr.shape[0] if self.data_format == "CHW" else arr.shape[-1]
        mean = self.mean[:c]
        std = self.std[:c]
        if self.data_format == "CHW":
            return (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
        return (arr - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        arr = np.asarray(img, dtype=np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + (arr.shape[-1],)
        return np.asarray(jax.image.resize(arr, out_shape, "bilinear"))


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[..., i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else \
                self.padding[0]
            arr = np.pad(arr, [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)],
                         mode="constant")
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        h, w = arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = arr[..., i:i + ch, j:j + cw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(arr)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size)(img)
