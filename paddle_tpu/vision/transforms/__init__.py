"""vision transforms (parity: python/paddle/vision/transforms/) —
numpy-based, CHW float arrays."""

from __future__ import annotations

import numbers
from typing import List, Sequence

import numpy as np


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        c = arr.shape[0] if self.data_format == "CHW" else arr.shape[-1]
        mean = self.mean[:c]
        std = self.std[:c]
        if self.data_format == "CHW":
            return (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
        return (arr - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        arr = np.asarray(img, dtype=np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + (arr.shape[-1],)
        return np.asarray(jax.image.resize(arr, out_shape, "bilinear"))


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[..., i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else \
                self.padding[0]
            arr = np.pad(arr, [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)],
                         mode="constant")
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        h, w = arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop = arr[..., i:i + ch, j:j + cw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(arr)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size)(img)


_LUMA_WEIGHTS = np.array([0.299, 0.587, 0.114], np.float32)
_T_YIQ = np.array([[0.299, 0.587, 0.114],
                   [0.596, -0.274, -0.321],
                   [0.211, -0.523, 0.311]], np.float32)
_T_YIQ_INV = np.linalg.inv(_T_YIQ)


def _rgb_to_gray(arr):
    """CHW luma; 1-channel input passes through (already gray)."""
    if arr.shape[0] == 1:
        return arr[:1]
    return np.tensordot(_LUMA_WEIGHTS, arr[:3], axes=1)[None]


def _jitter_alpha(value):
    """Upstream factor range: uniform(max(0, 1-v), 1+v) — never
    negative, so value > 1 is valid and never inverts the image."""
    return np.random.uniform(max(0.0, 1.0 - value), 1.0 + value)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(arr[..., ::-1, :])
        return arr


class Pad(BaseTransform):
    """Pad CHW image (int, (pad_w, pad_h), or 4-tuple l/t/r/b)."""

    def __init__(self, padding, fill=0, padding_mode="constant",
                 keys=None):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = tuple(int(p) for p in padding)
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = np.asarray(img)
        l, t, r, b = self.padding
        cfg = [(0, 0)] * (arr.ndim - 2) + [(t, b), (l, r)]
        if self.padding_mode == "constant":
            fill = self.fill
            if isinstance(fill, (list, tuple)):
                # per-channel fill: pad with 0 then paint the border
                out = np.pad(arr, cfg, mode="constant")
                fv = np.asarray(fill, arr.dtype).reshape(-1, 1, 1)
                h, w = arr.shape[-2:]
                mask = np.ones(out.shape[-2:], bool)
                mask[t:t + h, l:l + w] = False
                out = np.where(mask, fv, out)
                return out.astype(arr.dtype)
            return np.pad(arr, cfg, mode="constant",
                          constant_values=fill)
        mode = {"reflect": "reflect", "edge": "edge",
                "symmetric": "symmetric"}[self.padding_mode]
        return np.pad(arr, cfg, mode=mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        gray = _rgb_to_gray(arr)
        if self.num_output_channels == 3:
            gray = np.repeat(gray, 3, axis=0)
        return gray


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = _jitter_alpha(self.value)
        return np.clip(np.asarray(img, np.float32) * alpha,
                       0, None)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = np.asarray(img, np.float32)
        alpha = _jitter_alpha(self.value)
        mean = arr.mean()
        return np.clip(mean + alpha * (arr - mean), 0, None)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = np.asarray(img, np.float32)
        gray = _rgb_to_gray(arr)
        alpha = _jitter_alpha(self.value)
        out = np.clip(gray + alpha * (arr[:3] - gray), 0, None)
        if arr.shape[0] > 3:   # alpha channel untouched
            out = np.concatenate([out, arr[3:]], axis=0)
        return out


class HueTransform(BaseTransform):
    """Approximate hue rotation via the YIQ color rotation matrix."""

    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = np.asarray(img, np.float32)
        if arr.shape[0] == 1:
            return arr            # gray input: hue is a no-op
        theta = np.random.uniform(-self.value, self.value) * 2 * np.pi
        c, s = np.cos(theta), np.sin(theta)
        rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
        t_rgb = _T_YIQ_INV @ rot @ _T_YIQ
        out = np.clip(np.einsum("ij,jhw->ihw", t_rgb, arr[:3]), 0, None)
        if arr.shape[0] > 3:   # alpha channel untouched
            out = np.concatenate([out, arr[3:]], axis=0)
        return out


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self._ts = [BrightnessTransform(brightness),
                    ContrastTransform(contrast),
                    SaturationTransform(saturation),
                    HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self._ts))
        for i in order:
            img = self._ts[i]._apply_image(img)
        return img


class RandomRotation(BaseTransform):
    """Nearest-neighbor rotation by a random angle in degrees."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)
        self.fill = fill

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        c, s = np.cos(angle), np.sin(angle)
        h, w = arr.shape[-2:]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(h), np.arange(w),
                             indexing="ij")
        ys = c * (yy - cy) - s * (xx - cx) + cy
        xs = s * (yy - cy) + c * (xx - cx) + cx
        yi = np.round(ys).astype(np.int64)
        xi = np.round(xs).astype(np.int64)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yi = np.clip(yi, 0, h - 1)
        xi = np.clip(xi, 0, w - 1)
        out = arr[..., yi, xi]
        return np.where(valid, out, self.fill).astype(arr.dtype)


class RandomErasing(BaseTransform):
    """Cutout-style random rectangle erase (upstream RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.array(img, copy=True)   # dtype preserved
        h, w = arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                y = np.random.randint(0, h - eh + 1)   # edge-inclusive
                x = np.random.randint(0, w - ew + 1)
                arr[..., y:y + eh, x:x + ew] = np.asarray(
                    self.value).astype(arr.dtype)
                return arr
        return arr


from . import functional  # noqa  (stateless forms)
