"""vision.transforms.functional (parity:
python/paddle/vision/transforms/functional.py) — stateless forms of
the class transforms, numpy CHW."""

from __future__ import annotations

import numpy as np

from . import (CenterCrop, Grayscale, Pad, _rgb_to_gray, _T_YIQ,
               _T_YIQ_INV)
from . import to_tensor, normalize, resize  # noqa  (re-export)


def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[..., :, ::-1])


def vflip(img):
    return np.ascontiguousarray(np.asarray(img)[..., ::-1, :])


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)._apply_image(img)


def crop(img, top, left, height, width):
    return np.asarray(img)[..., top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)._apply_image(np.asarray(img))


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)._apply_image(img)


def adjust_brightness(img, brightness_factor):
    return np.clip(np.asarray(img, np.float32) * brightness_factor,
                   0, None)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img, np.float32)
    mean = arr.mean()
    return np.clip(mean + contrast_factor * (arr - mean), 0, None)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img, np.float32)
    gray = _rgb_to_gray(arr)
    out = np.clip(gray + saturation_factor * (arr[:3] - gray), 0, None)
    if arr.shape[0] > 3:
        out = np.concatenate([out, arr[3:]], axis=0)
    return out


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor is not in [-0.5, 0.5]")
    arr = np.asarray(img, np.float32)
    if arr.shape[0] == 1:
        return arr
    theta = hue_factor * 2 * np.pi
    c, s = np.cos(theta), np.sin(theta)
    rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
    t_rgb = _T_YIQ_INV @ rot @ _T_YIQ
    out = np.clip(np.einsum("ij,jhw->ihw", t_rgb, arr[:3]), 0, None)
    if arr.shape[0] > 3:
        out = np.concatenate([out, arr[3:]], axis=0)
    return out


def erase(img, i, j, h, w, v, inplace=False):
    arr = np.asarray(img) if inplace else np.array(img, copy=True)
    arr[..., i:i + h, j:j + w] = np.asarray(v).astype(arr.dtype)
    return arr
