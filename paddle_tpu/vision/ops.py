"""Detection operators (parity: python/paddle/vision/ops.py —
SURVEY.md §2.2 `paddle.vision`; the PP-YOLOE/detection slice of
BASELINE.json config 5).

TPU-first design notes:
- ``nms`` runs a **fixed-iteration masked suppression loop** (no
  data-dependent shapes): under jit it returns a padded index vector +
  valid count; the eager wrapper trims to the dynamic result paddle
  returns.
- ``roi_align`` is pure gather + bilinear arithmetic — differentiable
  and fusable by XLA (upstream needs a handwritten CUDA kernel pair).
- ``yolo_box``/``box_coder`` are elementwise decodes — free on the VPU.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from ..ops._primitive import primitive, unwrap
from .. import ops as _ops
from ..nn.layer import Layer


# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------
def _box_area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0)


def _pairwise_iou(a, b):
    """a: [N,4], b: [M,4] (x1,y1,x2,y2) → [N,M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _box_area(a)[:, None] + _box_area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


@primitive
def box_iou(boxes1, boxes2):
    return _pairwise_iou(boxes1, boxes2)


def _nms_mask(boxes, scores, iou_threshold: float):
    """Fixed-shape greedy NMS: returns keep mask [N] (bool), computed
    with a lax.fori_loop over N iterations — jit-safe."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    iou = _pairwise_iou(sorted_boxes, sorted_boxes)

    def body(i, alive):
        # if candidate i still alive, kill all later boxes with IoU>thr
        kill = (iou[i] > iou_threshold) & \
            (jnp.arange(n) > i) & alive[i]
        return alive & ~kill

    alive = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # un-sort the mask back to input order
    keep = jnp.zeros((n,), bool).at[order].set(alive)
    return keep, order, alive


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None):
    """paddle.vision.ops.nms parity: returns kept indices sorted by
    descending score. Batched-per-category when category_idxs given."""
    b = unwrap(boxes)
    s = unwrap(scores) if scores is not None else None
    if s is None:
        s = jnp.arange(b.shape[0], 0, -1, dtype=b.dtype)  # keep order
    if category_idxs is not None:
        # offset trick: shift boxes per category so they never overlap
        c = unwrap(category_idxs).astype(b.dtype)
        offset = (c * (jnp.max(b) + 1.0))[:, None]
        b = b + offset
    keep, order, alive = _nms_mask(b, s, iou_threshold)
    # eager path: trim to the dynamic result
    alive_np = np.asarray(alive)
    order_np = np.asarray(order)
    kept = order_np[alive_np]          # already score-descending
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, dtype=jnp.int64))


def nms_padded(boxes, scores, iou_threshold: float, max_out: int):
    """jit-safe NMS: (indices[max_out] padded with -1, valid_count).
    This is the form detection heads compile into a TPU program."""
    b, s = unwrap(boxes), unwrap(scores)
    keep, order, alive = _nms_mask(b, s, iou_threshold)
    # stable-select the first max_out alive entries of `order`;
    # suppressed/overflow entries scatter to a dummy slot [max_out]
    alive_rank = jnp.cumsum(alive.astype(jnp.int32)) - 1
    valid = alive & (alive_rank < max_out)
    buf = jnp.full((max_out + 1,), -1, dtype=jnp.int64)
    tgt = jnp.where(valid, alive_rank, max_out)
    buf = buf.at[tgt].set(jnp.where(valid, order, -1))
    count = jnp.minimum(jnp.sum(alive.astype(jnp.int32)), max_out)
    return Tensor(buf[:max_out]), Tensor(count)


def multiclass_nms(bboxes, scores, score_threshold: float = 0.05,
                   nms_threshold: float = 0.45, keep_top_k: int = 100,
                   nms_top_k: int = 400):
    """Per-class NMS + global top-k (detection postprocess).
    bboxes: [N,4]; scores: [C,N]. Returns [M,6] (label, score, box)."""
    b = np.asarray(unwrap(bboxes))
    s = np.asarray(unwrap(scores))
    results = []
    for c in range(s.shape[0]):
        mask = s[c] > score_threshold
        if not mask.any():
            continue
        cb, cs = b[mask], s[c][mask]
        if nms_top_k > 0 and cb.shape[0] > nms_top_k:
            top = np.argsort(-cs)[:nms_top_k]
            cb, cs = cb[top], cs[top]
        kept = np.asarray(
            nms(Tensor(cb), nms_threshold, Tensor(cs)).numpy())
        for i in kept:
            results.append([float(c), float(cs[i]), *cb[i].tolist()])
    if not results:
        return Tensor(np.zeros((0, 6), np.float32))
    out = np.asarray(results, np.float32)
    out = out[np.argsort(-out[:, 1])][:keep_top_k]
    return Tensor(out)


# ---------------------------------------------------------------------------
# RoI ops
# ---------------------------------------------------------------------------
@primitive
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """x: [N,C,H,W]; boxes: [R,4] (x1,y1,x2,y2 in image coords);
    boxes_num: [N] rois per image. Differentiable bilinear pooling."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    # image index per roi from boxes_num
    img_idx = jnp.repeat(jnp.arange(N), boxes_num,
                         total_repeat_length=R)

    off = 0.5 if aligned else 0.0
    bx = boxes * spatial_scale
    x1, y1, x2, y2 = bx[:, 0] - off, bx[:, 1] - off, \
        bx[:, 2] - off, bx[:, 3] - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    bin_h, bin_w = rh / ph, rw / pw
    # sampling_ratio=-1: upstream adapts the lattice per RoI
    # (ceil(roi_size/output_size)), which is data-dependent and
    # incompatible with XLA static shapes.  We use a fixed 2x2 lattice —
    # the detectron2/torchvision default — so outputs diverge from the
    # adaptive reference for RoIs much larger than the output grid.
    # Pass an explicit sampling_ratio for exact parity at a known scale.
    sr = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, ph, sr] y coords, [R, pw, sr] x coords
    sy = (y1[:, None, None] + (jnp.arange(ph)[None, :, None]) *
          bin_h[:, None, None] +
          (jnp.arange(sr)[None, None, :] + 0.5) / sr *
          bin_h[:, None, None])
    sx = (x1[:, None, None] + (jnp.arange(pw)[None, :, None]) *
          bin_w[:, None, None] +
          (jnp.arange(sr)[None, None, :] + 0.5) / sr *
          bin_w[:, None, None])

    def bilinear(img, yy, xx):
        """img: [C,H,W]; yy,xx: [...]→ [C, ...]"""
        yy = jnp.clip(yy, 0, H - 1)
        xx = jnp.clip(xx, 0, W - 1)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1_ = jnp.minimum(y0 + 1, H - 1)
        x1_ = jnp.minimum(x0 + 1, W - 1)
        wy = yy - y0
        wx = xx - x0
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1_]
        v10 = img[:, y1_, x0]
        v11 = img[:, y1_, x1_]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                v10 * wy * (1 - wx) + v11 * wy * wx)

    def per_roi(r):
        img = x[img_idx[r]]                       # [C,H,W]
        yy = sy[r][:, None, :, None]              # [ph,1,sr,1]
        xx = sx[r][None, :, None, :]              # [1,pw,1,sr]
        yy = jnp.broadcast_to(yy, (ph, pw, sr, sr))
        xx = jnp.broadcast_to(xx, (ph, pw, sr, sr))
        vals = bilinear(img, yy, xx)              # [C,ph,pw,sr,sr]
        return jnp.mean(vals, axis=(-1, -2))      # [C,ph,pw]

    return jax.vmap(per_roi)(jnp.arange(R))


@primitive
def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Max pooling over roi bins (quantized boundaries)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    img_idx = jnp.repeat(jnp.arange(N), boxes_num,
                         total_repeat_length=R)
    bx = jnp.round(boxes * spatial_scale).astype(jnp.int32)

    # fixed sample lattice (jit-safe): sample a dense grid per bin and
    # max-reduce; grid of 4 samples per bin side approximates the
    # dynamic quantized pooling
    sr = 4

    def per_roi(r):
        img = x[img_idx[r]]
        x1, y1, x2, y2 = bx[r, 0], bx[r, 1], bx[r, 2], bx[r, 3]
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        yy = y1 + (jnp.arange(ph * sr) + 0.5) / (ph * sr) * rh
        xx = x1 + (jnp.arange(pw * sr) + 0.5) / (pw * sr) * rw
        yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
        xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
        patch = img[:, yi][:, :, xi]              # [C, ph*sr, pw*sr]
        patch = patch.reshape(C, ph, sr, pw, sr)
        return jnp.max(patch, axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(R))


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


# ---------------------------------------------------------------------------
# YOLO decode + box coder
# ---------------------------------------------------------------------------
@primitive
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """Decode a YOLO head: x [N, na*(5+nc), H, W], img_size [N,2] (h,w)
    → (boxes [N, na*H*W, 4], scores [N, na*H*W, nc])."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = anchors.shape[0]
    N, _, H, W = x.shape
    nc = class_num
    feat = x.reshape(N, na, 5 + nc, H, W)
    gx = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (sig(feat[:, :, 0]) * scale_x_y -
          (scale_x_y - 1) / 2 + gx) / W
    by = (sig(feat[:, :, 1]) * scale_x_y -
          (scale_x_y - 1) / 2 + gy) / H
    in_w = W * downsample_ratio
    in_h = H * downsample_ratio
    bw = jnp.exp(feat[:, :, 2]) * anchors[None, :, 0, None, None] / in_w
    bh = jnp.exp(feat[:, :, 3]) * anchors[None, :, 1, None, None] / in_h
    obj = sig(feat[:, :, 4])
    cls = sig(feat[:, :, 5:])
    scores = obj[:, :, None] * cls                # [N,na,nc,H,W]
    # conf threshold zeroes scores (fixed shape; no dynamic filtering)
    scores = jnp.where(scores > conf_thresh, scores, 0.0)
    imh = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    imw = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [N,na,H,W,4]
    boxes = boxes.reshape(N, na * H * W, 4)
    scores = jnp.moveaxis(scores, 2, -1).reshape(N, na * H * W, nc)
    return boxes, scores


@primitive
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """SSD-style box encode/decode (upstream box_coder op)."""
    pb = prior_box
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,), pb.dtype)
        vx, vy, vw, vh = var
    elif prior_box_var.ndim == 1:
        vx, vy, vw, vh = (prior_box_var[i] for i in range(4))
    else:
        vx, vy = prior_box_var[:, 0], prior_box_var[:, 1]
        vw, vh = prior_box_var[:, 2], prior_box_var[:, 3]
    if code_type == "encode_center_size":
        tb = target_box
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None]) / pw[None] / vx
        oy = (tcy[:, None] - pcy[None]) / ph[None] / vy
        ow = jnp.log(tw[:, None] / pw[None]) / vw
        oh = jnp.log(th[:, None] / ph[None]) / vh
        return jnp.stack([ox, oy, ow, oh], axis=-1)
    # decode
    tb = target_box  # [R,4] deltas
    dcx = vx * tb[..., 0] * pw + pcx
    dcy = vy * tb[..., 1] * ph + pcy
    dw = jnp.exp(vw * tb[..., 2]) * pw
    dh = jnp.exp(vh * tb[..., 3]) * ph
    return jnp.stack([dcx - dw / 2 + norm * 0.5,
                      dcy - dh / 2 + norm * 0.5,
                      dcx + dw / 2 - norm * 0.5,
                      dcy + dh / 2 - norm * 0.5], axis=-1)


def distribute_fpn_proposals(fpn_rois, min_level, max_level,
                             refer_level, refer_scale,
                             rois_num=None):
    """Assign RoIs to FPN levels by scale (eager, dynamic output —
    detection postprocess runs on host)."""
    rois = np.asarray(unwrap(fpn_rois))
    w = np.maximum(rois[:, 2] - rois[:, 0], 0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois = []
    restore = np.argsort(
        np.concatenate([np.where(lvl == l)[0]
                        for l in range(min_level, max_level + 1)]))
    nums = []
    for l in range(min_level, max_level + 1):
        sel = lvl == l
        multi_rois.append(Tensor(rois[sel]))
        nums.append(int(sel.sum()))
    return multi_rois, Tensor(restore.astype(np.int64)), \
        Tensor(np.asarray(nums, np.int32))


@primitive
def deform_conv2d_op(x, offset, weight, mask=None, stride=1, padding=0,
                     dilation=1, deformable_groups=1, groups=1):
    """Deformable conv v2 via bilinear sampling + matmul (DCNv2 when
    mask given).  x [N,C,H,W], offset [N, 2*dg*kh*kw, Ho, Wo]."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    N, C, H, W = x.shape
    O, Cg, kh, kw = weight.shape
    Ho = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
        // stride[0] + 1
    Wo = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
        // stride[1] + 1
    K = kh * kw
    # base sampling locations per output pixel/kernel tap
    oy = jnp.arange(Ho) * stride[0] - padding[0]
    ox = jnp.arange(Wo) * stride[1] - padding[1]
    ky = jnp.arange(kh) * dilation[0]
    kx = jnp.arange(kw) * dilation[1]
    base_y = oy[:, None, None, None] + ky[None, None, :, None]
    base_x = ox[None, :, None, None] + kx[None, None, None, :]
    base_y = jnp.broadcast_to(base_y, (Ho, Wo, kh, kw)).astype(x.dtype)
    base_x = jnp.broadcast_to(base_x, (Ho, Wo, kh, kw)).astype(x.dtype)
    off = offset.reshape(N, deformable_groups, K, 2, Ho, Wo)
    m = None if mask is None else \
        mask.reshape(N, deformable_groups, K, Ho, Wo)

    def sample_img(img, yy, xx):
        """img [C,H,W]; yy/xx [...]: bilinear with zero padding OOB."""
        valid = (yy > -1) & (yy < H) & (xx > -1) & (xx < W)
        yy = jnp.clip(yy, 0, H - 1)
        xx = jnp.clip(xx, 0, W - 1)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        wy, wx = yy - y0, xx - x0
        v = (img[:, y0, x0] * (1 - wy) * (1 - wx) +
             img[:, y0, x1] * (1 - wy) * wx +
             img[:, y1, x0] * wy * (1 - wx) +
             img[:, y1, x1] * wy * wx)
        return v * valid.astype(img.dtype)

    cpg = C // deformable_groups  # channels per deformable group

    def per_image(n):
        cols = []
        for g in range(deformable_groups):
            dy = off[n, g, :, 0].transpose(1, 2, 0).reshape(Ho, Wo,
                                                            kh, kw)
            dx = off[n, g, :, 1].transpose(1, 2, 0).reshape(Ho, Wo,
                                                            kh, kw)
            yy = base_y + dy
            xx = base_x + dx
            img = x[n, g * cpg:(g + 1) * cpg]
            v = sample_img(img, yy, xx)  # [cpg,Ho,Wo,kh,kw]
            if m is not None:
                mm = m[n, g].transpose(1, 2, 0).reshape(Ho, Wo, kh, kw)
                v = v * mm[None]
            cols.append(v)
        col = jnp.concatenate(cols, axis=0)      # [C,Ho,Wo,kh,kw]
        col = col.transpose(1, 2, 0, 3, 4).reshape(Ho * Wo, C * K)
        wmat = weight.reshape(O, Cg * K)
        if groups == 1:
            out = col @ wmat.T                    # [Ho*Wo, O]
        else:
            og = O // groups
            outs = []
            for g in range(groups):
                cg = col.reshape(Ho * Wo, C, K)[
                    :, g * Cg:(g + 1) * Cg].reshape(Ho * Wo, Cg * K)
                outs.append(cg @ wmat[g * og:(g + 1) * og].T)
            out = jnp.concatenate(outs, axis=-1)
        return out.T.reshape(O, Ho, Wo)

    return jax.vmap(per_image)(jnp.arange(N))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    out = deform_conv2d_op(x, offset, weight, mask, stride=stride,
                           padding=padding, dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)
    if bias is not None:
        out = _ops.add(out, _ops.reshape(bias, [1, -1, 1, 1]))
    return out


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *kernel_size],
            attr=weight_attr, default_initializer=I.XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)
