"""Dynamic-shape handling for XLA: bucketing + padding.

SURVEY.md §7.3 hard part 3: XLA compiles one program per input shape,
so variable-length/size data (detection images, ragged text) must be
bucketed and padded to a small set of canonical shapes.  Upstream has
no equivalent (CUDA kernels take any shape); this is a TPU-native
component, used by the ViT/PP-YOLOE-class configs.

- ``shape_bucket(n, buckets)``: smallest bucket >= n.
- ``BucketBatchSampler``: groups sample indices so each batch comes
  from one length bucket (minimises padding waste) — same interface as
  io.BatchSampler.
- ``pad_batch(arrays, buckets, axis, pad_value)``: pad each array (and
  return the mask) to its bucket boundary.
- ``PadToBuckets``: collate_fn wrapper applying pad_batch to a field.
"""

from __future__ import annotations

import bisect
from typing import Callable, List, Optional, Sequence

import numpy as np

from .sampler import Sampler


def shape_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (last bucket if n exceeds them all)."""
    buckets = sorted(buckets)
    i = bisect.bisect_left(buckets, n)
    return buckets[min(i, len(buckets) - 1)]


def pad_batch(arrays: Sequence[np.ndarray], buckets: Sequence[int],
              axis: int = 0, pad_value=0):
    """Pad every array along ``axis`` to the common bucket boundary of
    the longest one.  Returns (stacked [B, ...], mask [B, L])."""
    longest = max(a.shape[axis] for a in arrays)
    target = shape_bucket(longest, buckets)
    out, mask = [], []
    for a in arrays:
        n = a.shape[axis]
        pad = [(0, 0)] * a.ndim
        pad[axis] = (0, max(target - n, 0))
        if n > target:  # exceeds the largest bucket: truncate
            sl = [slice(None)] * a.ndim
            sl[axis] = slice(0, target)
            a = a[tuple(sl)]
            n = target
        out.append(np.pad(a, pad, constant_values=pad_value))
        m = np.zeros(target, dtype=bool)
        m[:n] = True
        mask.append(m)
    return np.stack(out), np.stack(mask)


class BucketBatchSampler(Sampler):
    """Batch sampler grouping samples into size buckets.

    ``size_fn(idx) -> int`` gives each sample's size (e.g. seq length);
    batches are drawn within one bucket so the padded shape is shared —
    one XLA program per bucket instead of per unique length.
    """

    def __init__(self, dataset, batch_size: int,
                 buckets: Sequence[int],
                 size_fn: Optional[Callable[[int], int]] = None,
                 shuffle: bool = False, drop_last: bool = False,
                 seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.buckets = sorted(buckets)
        self.size_fn = size_fn or \
            (lambda i: int(np.asarray(dataset[i][0]).shape[0]))
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._epoch = 0
        self._seed = seed
        self._assign = None

    def _assignments(self) -> dict:
        if self._assign is None:
            self._assign = {}
            for i in range(len(self.dataset)):
                b = shape_bucket(self.size_fn(i), self.buckets)
                self._assign.setdefault(b, []).append(i)
        return self._assign

    def set_epoch(self, epoch: int):
        self._epoch = epoch

    def __iter__(self):
        groups = self._assignments()
        batches = []
        rng = np.random.RandomState(self._seed + self._epoch)
        for b, idxs in sorted(groups.items()):
            idxs = list(idxs)
            if self.shuffle:
                rng.shuffle(idxs)
            for k in range(0, len(idxs), self.batch_size):
                chunk = idxs[k:k + self.batch_size]
                if len(chunk) < self.batch_size and self.drop_last:
                    continue
                batches.append(chunk)
        if self.shuffle:
            rng.shuffle(batches)
        return iter(batches)

    def __len__(self):
        groups = self._assignments()
        n = 0
        for idxs in groups.values():
            if self.drop_last:
                n += len(idxs) // self.batch_size
            else:
                n += (len(idxs) + self.batch_size - 1) // self.batch_size
        return n


class PadToBuckets:
    """collate_fn: pads field 0 (or ``field``) of each sample to its
    bucket along ``axis`` and appends the validity mask."""

    def __init__(self, buckets: Sequence[int], axis: int = 0,
                 pad_value=0, field: int = 0):
        self.buckets = sorted(buckets)
        self.axis = axis
        self.pad_value = pad_value
        self.field = field

    def __call__(self, batch):
        from .dataloader import default_collate_fn
        from ..tensor import Tensor
        seqs = [np.asarray(s[self.field]) for s in batch]
        padded, mask = pad_batch(seqs, self.buckets, self.axis,
                                 self.pad_value)
        rest = [[v for j, v in enumerate(s) if j != self.field]
                for s in batch]
        collated = default_collate_fn(rest) if rest[0] else []
        return (Tensor(padded), *collated, Tensor(mask))
