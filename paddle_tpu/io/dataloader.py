"""DataLoader (parity: python/paddle/io/dataloader/).

Two reader paths, mirroring upstream's Python-workers + C++
BlockingQueue split:

- ``num_workers > 0`` (map-style datasets): the **native reader** —
  N worker threads run indexing + collate and enqueue batches into the
  C++ blocking queue (``paddle_tpu.native``), which copies arrays into
  aligned native memory with the GIL released (see io/native_reader.py).
- otherwise: a single-thread Python prefetch queue, enough to overlap
  host batching with the async H2D jax already provides.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, List, Optional

import numpy as np

from ..tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler
from .staging import stage_batch


def default_collate_fn(batch):
    """Stack samples; mirrors paddle's default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.numpy()) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch])
                for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return Tensor(np.asarray(batch))


class _PrefetchIterator:
    def __init__(self, gen_fn, buffer_size: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
        self._done = object()
        self._err = None
        self._stop = threading.Event()

        def worker():
            try:
                for item in gen_fn():
                    # bounded put with a stop check so an abandoned
                    # iterator doesn't park this thread (and its buffered
                    # batches) forever
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=0.2)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                # the sentinel must arrive or the consumer blocks forever;
                # keep trying unless the iterator was abandoned
                while not self._stop.is_set():
                    try:
                        self._q.put(self._done, timeout=0.2)
                        break
                    except queue.Full:
                        continue

        self._t = threading.Thread(target=worker, daemon=True)
        self._t.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self):
        self._stop.set()

    def __del__(self):
        self._stop.set()


class _DevicePrefetcher:
    """Host→HBM double buffering (SURVEY.md §2.1 DataLoader row;
    upstream's use_buffer_reader / CUDA double-buffer reader).

    Keeps ``depth`` batches in flight on the device: each batch is
    ``jax.device_put`` as soon as the host thread produces it, so the
    H2D transfer of batch N+1 overlaps the compute of batch N (jax
    transfers are async; dispatching the put is enough to start it).
    On CPU the put is a no-op alias — safe everywhere.

    Under step folding (``Model.fit(steps_per_dispatch=K)`` sets the
    loader's ``_fold_hint``) per-batch eager staging is skipped
    (``stage=False``): the fold engine stacks K batches and issues ONE
    batched ``device_put`` for the whole ``[K, ...]`` group
    (io/staging.py ``stack_to_device``), so staging each batch here
    first would just double the transfer dispatches."""

    def __init__(self, inner, depth: int = 2, stage: bool = True):
        import collections
        self._inner = inner
        self._it = iter(inner)
        self._buf = collections.deque()
        self._depth = max(1, depth)
        self._exhausted = False
        self._pending_err = None
        self._do_stage = stage

    def __getattr__(self, name):
        # transparent wrapper: the inner iterator's surface (native
        # reader close()/stats()/_threads, prefetch _stop, ...) stays
        # reachable
        return getattr(self.__dict__["_inner"], name)

    def _stage(self, item):
        # the single host→device staging path shared with the hapi
        # Model hot loop (io/staging.py)
        return stage_batch(item) if self._do_stage else item

    def _fill(self):
        while not self._exhausted and len(self._buf) < self._depth:
            try:
                self._buf.append(self._stage(next(self._it)))
            except StopIteration:
                self._exhausted = True
            except BaseException:
                # an iterator that raised is finished (iterator
                # protocol); never pull it again
                self._exhausted = True
                raise

    def __iter__(self):
        return self

    def __next__(self):
        if self._pending_err is not None and not self._buf:
            # drain buffered good batches first; the error surfaces at
            # the position of the batch that caused it
            err, self._pending_err = self._pending_err, None
            raise err
        self._fill()
        if not self._buf:
            raise StopIteration
        out = self._buf.popleft()
        try:
            self._fill()   # start the next H2D now
        except BaseException as e:
            # don't lose the good batch already popped: surface the
            # producer's error at ITS position, on the next call.
            # The inner iterator has RAISED — per the iterator
            # protocol it is finished; pulling it again would yield
            # undefined results (the native reader, for one, drains
            # its closed queue and masks the real error with "lost
            # batches"), so mark exhausted to pin the next _fill to a
            # no-op.
            self._pending_err = e
            self._exhausted = True
        return out


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def _generate(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if self.batch_size and len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield self.collate_fn(batch)

    def _generate_iterable_workers(self):
        """IterableDataset with num_workers > 0: each worker THREAD
        iterates the dataset with its thread-local WorkerInfo set, so
        a dataset that shards by ``get_worker_info()`` (the upstream
        contract) splits the stream; batching/drop_last apply per
        worker, batches interleave in completion order."""
        import queue as _q
        from .dataset import WorkerInfo, _set_worker_info
        out = _q.Queue(maxsize=self.num_workers
                       * max(1, self.prefetch_factor))
        _END = object()

        def work(wid):
            try:
                _set_worker_info(WorkerInfo(wid, self.num_workers,
                                            self.dataset))
                if self.worker_init_fn is not None:
                    self.worker_init_fn(wid)
                batch = []
                for sample in self.dataset:
                    batch.append(sample)
                    if self.batch_size and len(batch) == self.batch_size:
                        out.put(self.collate_fn(batch))
                        batch = []
                if batch and not self.drop_last:
                    out.put(self.collate_fn(batch))
            except BaseException as e:
                out.put(e)
            finally:
                out.put(_END)

        import threading as _t
        threads = [_t.Thread(target=work, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        done = 0
        while done < self.num_workers:
            item = out.get()
            if item is _END:
                done += 1
            elif isinstance(item, BaseException):
                raise item
            else:
                yield item

    def __iter__(self):
        # step folding: the hapi fit loop advertises its fold through
        # _fold_hint; the prefetcher then keeps batches host-side and
        # the fold engine's stacked device_put becomes the single H2D
        # point for the whole K-batch group
        stage = getattr(self, "_fold_hint", 1) <= 1
        if self._iterable_mode and self.num_workers > 0:
            gen = self._generate_iterable_workers
            return _DevicePrefetcher(
                _PrefetchIterator(gen, self.prefetch_factor),
                stage=stage) \
                if self.use_buffer_reader else gen()
        if (self.num_workers > 0 and not self._iterable_mode
                and self.batch_sampler is not None):
            from .. import native
            if native.available():
                from .native_reader import NativeMapIterator
                it = NativeMapIterator(
                    self.dataset, [list(b) for b in self.batch_sampler],
                    self.collate_fn, self.num_workers,
                    self.prefetch_factor, self.worker_init_fn)
                return _DevicePrefetcher(it, stage=stage) \
                    if self.use_buffer_reader else it
        if self.use_buffer_reader:
            return _DevicePrefetcher(
                _PrefetchIterator(self._generate, self.prefetch_factor),
                stage=stage)
        return self._generate()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)
