"""paddle.io parity: Dataset / DataLoader / samplers.

Upstream uses multiprocess workers + a C++ BlockingQueue feeding pinned
host memory (SURVEY.md §2.1 "DataLoader C++ core").  On TPU the input
pipeline is host-side numpy batching + async ``jax.device_put``
double-buffering; XLA overlaps the H2D copy with the previous step, so a
threaded prefetcher replaces the C++ queue (profiles will tell if a
native ring buffer is ever needed — §7.0 defers it).
"""

from .dataset import (  # noqa
    Dataset, IterableDataset, TensorDataset, ComposeDataset,
    ChainDataset, Subset, ConcatDataset, random_split,
    WorkerInfo, get_worker_info)
from .sampler import (  # noqa
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler, SubsetRandomSampler)
from .dataloader import DataLoader, default_collate_fn  # noqa
