"""Single host→device staging path (DESIGN-PERF.md).

Every host batch enters the device through here: the hapi
``Model._prepare_data`` hot loop and the DataLoader's device
double-buffer (``_DevicePrefetcher``) both stage through this module,
so the H2D story has one owner — one ``np.asarray`` view (zero-copy
for arrays already in host memory) followed by ONE async
``jax.device_put``.  The per-element ``jnp.asarray(np.asarray(d))``
round-trip the seed code did (host → device → trace-time convert) is
gone.
"""

from __future__ import annotations

import numpy as np

from ..tensor import Tensor


def to_device_value(d):
    """Host value → jax array via one async ``device_put``.

    ``Tensor`` inputs pass their device value through untouched; the
    put is dispatched asynchronously, so the H2D copy of this batch
    overlaps the compute of the previous step.
    """
    if isinstance(d, Tensor):
        return d._value
    import jax
    if isinstance(d, jax.Array):
        return d   # already device-resident: no D2H round trip
    if not isinstance(d, np.ndarray):
        d = np.asarray(d)
    return jax.device_put(d)


def to_device_values(seq):
    """Batch variant of :func:`to_device_value`: ONE async
    ``device_put`` covers every host leaf in the sequence (jax batches
    the transfers), Tensor leaves pass their device value through."""
    import jax
    vals = []
    host_idx = []
    for i, d in enumerate(seq):
        if isinstance(d, Tensor):
            vals.append(d._value)
        elif isinstance(d, jax.Array):
            vals.append(d)   # already device-resident
        else:
            host_idx.append(i)
            vals.append(d if isinstance(d, np.ndarray) else np.asarray(d))
    if host_idx:
        placed = jax.device_put([vals[i] for i in host_idx])
        for i, v in zip(host_idx, placed):
            vals[i] = v
    return vals


def stack_to_device(groups, shardings=None):
    """Stack K same-structure batches along a new leading axis — the
    staging path of the step-folding engine (the unified
    ``framework/dispatch.py`` path under ``Model.fit`` and
    ``DistributedRunner``): each tensor position becomes ONE
    ``[K, ...]`` stacked device array, and every position whose K
    leaves are still host memory rides a single batched async
    ``device_put``.  Positions already device-resident (a prefetcher
    that staged eagerly, direct Tensor feeds) stack with one
    ``jnp.stack`` dispatch instead — never a device→host round trip.

    ``shardings`` (mesh path): per-position ``NamedSharding`` (or None)
    the host leaves are placed with directly, so the folded mesh
    dispatch consumes batch arrays already laid out on their data axes
    instead of paying an in-program reshard of the whole ``[K, ...]``
    stack.
    """
    import jax
    import jax.numpy as jnp
    n = len(groups[0])
    out = [None] * n
    host_idx = []
    for i in range(n):
        vs = []
        all_host = True
        for g in groups:
            v = g[i]
            if isinstance(v, Tensor):
                v = v._value
            if isinstance(v, jax.Array):
                all_host = False
            elif not isinstance(v, np.ndarray):
                v = np.asarray(v)
            vs.append(v)
        if all_host:
            out[i] = np.stack(vs)
            host_idx.append(i)
        else:
            out[i] = jnp.stack([jnp.asarray(v) for v in vs])
    if host_idx:
        if shardings is not None:
            placed = jax.device_put(
                [out[i] for i in host_idx],
                [shardings[i] for i in host_idx])
        else:
            placed = jax.device_put([out[i] for i in host_idx])
        for i, v in zip(host_idx, placed):
            out[i] = v
    return out


def stage_batch(item):
    """Tree-map device staging for loader batches: start the async H2D
    copy for every Tensor leaf (device double-buffering — the transfer
    of batch N+1 overlaps the compute of batch N)."""
    import jax
    if isinstance(item, Tensor):
        return Tensor(jax.device_put(item._value))
    if isinstance(item, (list, tuple)):
        return type(item)(stage_batch(v) for v in item)
    if isinstance(item, dict):
        return {k: stage_batch(v) for k, v in item.items()}
    return item
