"""Datasets (parity: python/paddle/io/dataset.py)."""

from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lengths = {t.shape[0] for t in tensors}
        assert len(lengths) == 1, "tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        s = 0
        for d in self.datasets:
            s += len(d)
            self.cumulative_sizes.append(s)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import numpy as np
    n = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * n)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    assert sum(lengths) == n
    perm = np.random.permutation(n).tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l]))
        offset += l
    return out


# -- worker context (upstream paddle.io.get_worker_info) -------------------

class WorkerInfo:
    """Identity of the current DataLoader worker (upstream WorkerInfo:
    id / num_workers / dataset)."""

    def __init__(self, id: int, num_workers: int, dataset=None):
        self.id = int(id)
        self.num_workers = int(num_workers)
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


import threading as _threading

_WORKER_TLS = _threading.local()


def _set_worker_info(info) -> None:
    _WORKER_TLS.info = info


def get_worker_info():
    """None in the main process; a :class:`WorkerInfo` inside a
    DataLoader worker thread (the IterableDataset sharding contract;
    thread-local because the native reader's workers are threads)."""
    return getattr(_WORKER_TLS, "info", None)
