"""Multi-worker buffered reader over the native blocking queue.

The reference's DataLoader pairs Python worker processes with a C++
BlockingQueue/BufferedReader (SURVEY.md §2.1 "DataLoader C++ core");
here N worker *threads* run dataset indexing + collate and hand each
batch to ``paddle_tpu.native.NativeQueue``, which copies the arrays
into one aligned C++ allocation with the GIL released — so the heavy
memcpys overlap across workers, and the consumer reads sequential
aligned memory ready for host→HBM transfer.

Order is preserved (paddle semantics): batches carry a sequence number
and the consumer reorders through a small stash.

Lifecycle: worker threads deliberately hold NO reference to the
iterator — only to a shared ``_WorkerState`` — so an abandoned iterator
(e.g. ``break`` mid-epoch) is garbage-collected, its finalizer closes
the queue, blocked pushes return False, and the workers exit.
"""

from __future__ import annotations

import pickle
import threading
import traceback
import weakref
from typing import Any, Callable, List, Tuple

import numpy as np

from .. import native


def flatten_batch(obj) -> Tuple[List[np.ndarray], Any]:
    """Split a collated batch pytree into (arrays, skeleton)."""
    from ..tensor import Tensor
    arrays: List[np.ndarray] = []

    def rec(o):
        if isinstance(o, Tensor):
            arrays.append(np.asarray(o.numpy()))
            return ("t", len(arrays) - 1)
        if isinstance(o, np.ndarray):
            arrays.append(o)
            return ("a", len(arrays) - 1)
        if isinstance(o, tuple):
            return ("u", [rec(x) for x in o])
        if isinstance(o, list):
            return ("l", [rec(x) for x in o])
        if isinstance(o, dict):
            return ("d", {k: rec(v) for k, v in o.items()})
        return ("o", o)

    return arrays, rec(obj)


def unflatten_batch(arrays: List[np.ndarray], skel) -> Any:
    from ..tensor import Tensor
    tag, payload = skel
    if tag == "t":
        return Tensor(arrays[payload])
    if tag == "a":
        return arrays[payload]
    if tag == "u":
        return tuple(unflatten_batch(arrays, s) for s in payload)
    if tag == "l":
        return [unflatten_batch(arrays, s) for s in payload]
    if tag == "d":
        return {k: unflatten_batch(arrays, s) for k, s in payload.items()}
    return payload


_DONE = "__worker_done__"
_ERROR = "__error__"


class _WorkerState:
    """Everything the worker threads touch; no back-ref to the iterator."""

    def __init__(self, dataset, batches, collate_fn, queue,
                 worker_init_fn, num_workers=1):
        self.dataset = dataset
        self.batches = batches
        self.collate = collate_fn
        self.queue = queue
        self.worker_init_fn = worker_init_fn
        self.num_workers = num_workers
        self.cursor = 0
        self.lock = threading.Lock()

    def next_index(self):
        with self.lock:
            if self.cursor >= len(self.batches):
                return None
            i = self.cursor
            self.cursor += 1
            return i


def _pickle_exc(e: BaseException) -> bytes:
    """Pickle an exception, degrading to a RuntimeError that carries the
    formatted traceback when the original object won't pickle."""
    try:
        blob = pickle.dumps((_ERROR, e))
        pickle.loads(blob)  # some objects pickle but fail to unpickle
        return blob
    except Exception:
        return pickle.dumps((_ERROR, RuntimeError(
            "DataLoader worker raised (original exception not "
            "picklable):\n" + "".join(traceback.format_exception(e)))))


def _worker_main(state: _WorkerState, wid: int):
    q = state.queue
    try:
        from .dataset import WorkerInfo, _set_worker_info
        _set_worker_info(WorkerInfo(wid, state.num_workers,
                                    state.dataset))
        if state.worker_init_fn is not None:
            state.worker_init_fn(wid)
        while True:
            seq = state.next_index()
            if seq is None:
                break
            indices = state.batches[seq]
            batch = state.collate([state.dataset[i] for i in indices])
            arrays, skel = flatten_batch(batch)
            if not q.push(arrays, pickle.dumps((seq, skel))):
                return  # queue closed: consumer abandoned us
    except BaseException as e:  # propagate to consumer
        try:
            q.push([], _pickle_exc(e))
        except Exception:
            pass
    finally:
        try:
            q.push([], pickle.dumps((_DONE, wid)))
        except Exception:
            pass


class NativeMapIterator:
    """Ordered multi-worker iterator for map-style datasets."""

    def __init__(self, dataset, batch_indices: List[List[int]],
                 collate_fn: Callable, num_workers: int,
                 prefetch_factor: int = 2,
                 worker_init_fn: Callable = None):
        self._num_workers = max(1, num_workers)
        queue = native.NativeQueue(
            self._num_workers * max(1, prefetch_factor))
        self._queue = queue
        self._state = _WorkerState(dataset, batch_indices, collate_fn,
                                   queue, worker_init_fn,
                                   num_workers=self._num_workers)
        self._next_out = 0
        self._stash = {}
        self._done_workers = 0
        self._closed = False
        # if the iterator is dropped without exhausting/close(), unblock
        # and terminate the workers
        self._finalizer = weakref.finalize(self, queue.close)
        self._threads = [
            threading.Thread(target=_worker_main,
                             args=(self._state, w), daemon=True)
            for w in range(self._num_workers)
        ]
        for t in self._threads:
            t.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            # the iterator already terminated (error raised or
            # exhausted); draining the closed queue here would
            # deliver out-of-order leftovers
            raise StopIteration
        while True:
            if self._next_out in self._stash:
                arrays, skel = self._stash.pop(self._next_out)
                self._next_out += 1
                return unflatten_batch(arrays, skel)
            if self._done_workers >= self._num_workers:
                if self._stash:
                    # workers exited with gaps — shouldn't happen
                    raise RuntimeError("native reader lost batches")
                self.close()
                raise StopIteration
            got = self._queue.pop()
            if got is None:  # closed
                raise StopIteration
            arrays, blob = got
            key, payload = pickle.loads(blob)
            if key == _ERROR:
                self.close()
                raise payload
            if key == _DONE:
                self._done_workers += 1
                continue
            self._stash[key] = (arrays, payload)

    def close(self):
        self._closed = True
        self._queue.close()

    def stats(self):
        return self._queue.stats()
