"""paddle.signal (parity: python/paddle/signal.py — frame/overlap_add/
stft/istft).  Pure composition of reshape + jnp.fft; the framing is a
static strided gather so the whole pipeline jits and differentiates.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .ops._primitive import primitive, unwrap

__all__ = ["frame", "overlap_add", "stft", "istft"]


@primitive
def frame(x, frame_length, hop_length, axis=-1):
    """Slice ``x`` into overlapping frames along ``axis`` → a new
    trailing (paddle: axis=-1 → [..., frame_length, num_frames])."""
    if axis not in (-1, x.ndim - 1, 0):
        raise NotImplementedError("frame supports axis -1 or 0")
    if axis == 0 and x.ndim > 1:
        raise NotImplementedError("axis=0 framing expects 1D input")
    n = x.shape[-1]
    if frame_length > n:
        raise ValueError(
            f"frame_length ({frame_length}) exceeds the signal "
            f"length ({n})")
    num = 1 + (n - frame_length) // hop_length
    idx = (np.arange(frame_length)[:, None]
           + hop_length * np.arange(num)[None, :])
    out = x[..., idx]                    # [..., frame_length, num]
    return out


@primitive
def overlap_add(x, hop_length, axis=-1):
    """Inverse of frame: [..., frame_length, num_frames] → signal."""
    if axis not in (-1, x.ndim - 1):
        raise NotImplementedError("overlap_add supports axis=-1 only")
    fl = x.shape[-2]
    num = x.shape[-1]
    n = fl + hop_length * (num - 1)
    out = jnp.zeros(x.shape[:-2] + (n,), x.dtype)
    for f in range(num):                 # static unroll (num is small)
        out = out.at[..., f * hop_length:f * hop_length + fl].add(
            x[..., :, f])
    return out


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """Short-time Fourier transform (upstream paddle.signal.stft):
    returns [..., n_fft//2+1 (or n_fft), num_frames] complex."""
    from . import fft as _fft
    from .ops._primitive import apply_closure
    from .tensor import Tensor

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    xv = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    wv = None if window is None else unwrap(window)

    def _f(v, *maybe_w):
        w = maybe_w[0] if maybe_w else None
        if center:
            pad = n_fft // 2
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        num = 1 + (v.shape[-1] - n_fft) // hop_length
        idx = (np.arange(n_fft)[:, None]
               + hop_length * np.arange(num)[None, :])
        frames = v[..., idx]             # [..., n_fft, num]
        if w is not None:
            wfull = w
            if win_length != n_fft:
                lpad = (n_fft - win_length) // 2
                wfull = jnp.pad(w, (lpad, n_fft - win_length - lpad))
            frames = frames * wfull[..., :, None]
        frames = jnp.moveaxis(frames, -2, -1)   # [..., num, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.moveaxis(spec, -1, -2)        # [..., freq, num]

    args = [xv] + ([Tensor(wv)] if wv is not None else [])
    return apply_closure(_f, args, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope-normalised overlap-add."""
    if return_complex and onesided:
        raise ValueError(
            "return_complex=True requires onesided=False (a onesided "
            "spectrum reconstructs a real signal)")
    from .ops._primitive import apply_closure
    from .tensor import Tensor

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    xv = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    wv = None if window is None else unwrap(window)

    def _f(v, *maybe_w):
        w = maybe_w[0] if maybe_w else None
        spec = jnp.moveaxis(v, -2, -1)           # [..., num, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        if w is not None:
            wfull = w
            if win_length != n_fft:
                lpad = (n_fft - win_length) // 2
                wfull = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        else:
            wfull = jnp.ones((n_fft,), frames.dtype)
        frames = frames * wfull
        num = frames.shape[-2]
        n = n_fft + hop_length * (num - 1)
        sig = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        env = jnp.zeros((n,), frames.dtype)
        for f in range(num):
            sl = slice(f * hop_length, f * hop_length + n_fft)
            sig = sig.at[..., sl].add(frames[..., f, :])
            env = env.at[sl].add(wfull * wfull)
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad:n - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig

    args = [xv] + ([Tensor(wv)] if wv is not None else [])
    return apply_closure(_f, args, name="istft")
