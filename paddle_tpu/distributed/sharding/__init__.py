"""paddle.distributed.sharding (parity: python/paddle/distributed/
sharding/group_sharded.py — group_sharded_parallel / save_group_sharded_model)."""

from __future__ import annotations

from ..fleet.meta_parallel.sharding_parallel import (  # noqa
    GroupShardedStage2, GroupShardedStage3, GroupShardedOptimizerStage2,
    apply_sharding_stage)


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3)."""
    from ..fleet.base.topology import _get_hybrid_parallel_group
    hcg = _get_hybrid_parallel_group()
    size = hcg.get_sharding_parallel_world_size() if hcg else 1
    if level == "os":
        apply_sharding_stage(model, 1, max(size, 1))
        optimizer._sharded_state = True
        return model, optimizer, scaler
    if level == "os_g":
        opt = GroupShardedOptimizerStage2(model.parameters(), optimizer,
                                          group=group, offload=offload)
        wrapped = GroupShardedStage2(model, opt, group=group,
                                     sync_buffers=sync_buffers,
                                     buffer_max_size=buffer_max_size)
        return wrapped, opt, scaler
    if level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer=optimizer,
                                     group=group,
                                     sync_buffers=sync_buffers,
                                     segment_size=segment_size)
        optimizer._sharded_state = True
        return wrapped, optimizer, scaler
    raise ValueError(f"unknown level {level!r}; use os | os_g | p_g_os")


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ...framework.io import save
    os.makedirs(output, exist_ok=True)
    target = model._layers if hasattr(model, "_layers") else model
    save(target.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
