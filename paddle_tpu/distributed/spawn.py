"""paddle.distributed.spawn (parity: python/paddle/distributed/spawn.py).

Launches ``nprocs`` worker processes from Python (the programmatic
alternative to ``python -m paddle_tpu.distributed.launch``), sets the
paddle env contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT / PADDLE_MASTER) in
each child BEFORE the user function runs, and joins.

Uses the multiprocessing ``spawn`` start method — fork is unsafe once
jax has initialized a backend (upstream forbids fork after CUDA init
for the same reason)."""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
from typing import Sequence


class ProcessContext:
    def __init__(self, procs):
        self.processes = procs

    def join(self, timeout=None):
        """Join with failure monitoring: one crashed rank terminates
        the survivors (which would otherwise hang in rendezvous /
        collectives waiting for their dead peer) and raises."""
        import time as _time
        deadline = None if timeout is None else _time.time() + timeout
        while True:
            codes = [p.exitcode for p in self.processes]
            bad = [(p.name, c) for p, c in zip(self.processes, codes)
                   if c not in (0, None)]
            if bad:
                for p in self.processes:
                    if p.exitcode is None:
                        p.terminate()
                for p in self.processes:
                    p.join(10)
                raise RuntimeError(
                    f"distributed.spawn: worker(s) failed: {bad}")
            if all(c == 0 for c in codes):
                return True
            if deadline is not None and _time.time() > deadline:
                return False
            _time.sleep(0.2)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker(func, args, env):
    # env BEFORE any jax backend init in this fresh process
    os.environ.update(env)
    func(*args)


def spawn(func, args: Sequence = (), nprocs: int = -1, join: bool = True,
          daemon: bool = False, **options):
    """Run ``func(*args)`` in ``nprocs`` rank processes.  Rank identity
    arrives via the paddle env contract (read it with
    ``paddle.distributed.get_rank()`` / ``init_parallel_env()``)."""
    if nprocs <= 0:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    master = f"127.0.0.1:{_free_port()}"
    base = _free_port()
    endpoints = [f"127.0.0.1:{base + i}" for i in range(nprocs)]
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_MASTER": master,
        }
        env.update(options.get("env", {}))
        p = ctx.Process(target=_worker, args=(func, tuple(args), env),
                        daemon=daemon, name=f"spawn-rank{rank}")
        p.start()
        procs.append(p)
    context = ProcessContext(procs)
    if join:
        context.join()
    return context
