"""Process-level parallel environment (parity: python/paddle/distributed/
parallel.py — ParallelEnv, init_parallel_env, DataParallel).

Control plane: upstream rendezvouses through TCPStore and creates NCCL
communicators per group (SURVEY.md §3.3).  Here ``init_parallel_env``
maps the same env-var contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM
/ PADDLE_MASTER) onto ``jax.distributed.initialize`` — the coordination
service IS the TCPStore analog; mesh axes replace communicators.

``DataParallel`` needs no Reducer on TPU: gradients are averaged by a
``psum`` that XLA fuses into the backward (SURVEY.md §2.1 "DataParallel
Reducer" row).  The wrapper installs a dp sharding annotation and
averages grads across the dp axis eagerly when running multi-process.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..nn.layer import Layer


class ParallelEnv:
    """Reads the paddle launch env contract."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = int(os.environ.get(
            "FLAGS_selected_tpus",
            os.environ.get("FLAGS_selected_gpus", "0")).split(",")[0])
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                                "")

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def local_rank(self):
        return self._rank

    @property
    def nranks(self):
        return self._world_size

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def dev_id(self):
        return self._device_id


_parallel_env_initialized = [False]


def init_parallel_env():
    """Multi-host bring-up: jax.distributed.initialize with the paddle
    env contract.  Single-process (the common test path) is a no-op."""
    env = ParallelEnv()
    if _parallel_env_initialized[0]:
        return env
    if env.world_size > 1:
        master = os.environ.get("PADDLE_MASTER")
        if not master and env.trainer_endpoints:
            master = env.trainer_endpoints[0]
        jax.distributed.initialize(
            coordinator_address=master,
            num_processes=env.world_size,
            process_id=env.rank)
    _parallel_env_initialized[0] = True
    return env


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(ParallelEnv().rank)
    return ParallelEnv().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    return ParallelEnv().world_size


def is_initialized() -> bool:
    return _parallel_env_initialized[0]


class DataParallel(Layer):
    """paddle.DataParallel wrapper.

    On TPU the gradient sync is not a wrapper concern: under jit+mesh the
    dp ``psum`` is emitted by sharding propagation; in eager multi-process
    mode ``fused_allreduce_gradients`` (fleet utils) is called by the
    optimizer hook.  The wrapper therefore only (a) marks parameters with
    a replicated dist spec, (b) forwards attribute access, keeping
    upstream API semantics (including ``no_sync``)."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True
        for p in layers.parameters():
            p.is_distributed = False  # replicated under dp

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prev = self._grad_sync_enabled
            self._grad_sync_enabled = False
            try:
                yield
            finally:
                self._grad_sync_enabled = prev

        return ctx()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self

    @property
    def training(self):
        return self._layers.training

    @training.setter
    def training(self, v):
        pass
