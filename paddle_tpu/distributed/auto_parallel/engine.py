"""Auto-parallel Engine + dist.to_static (parity:
python/paddle/distributed/auto_parallel/engine.py and the 3.0-era
``paddle.distributed.to_static`` API — SURVEY.md §2.2 "Auto-parallel").

Upstream's Engine plans a distributed program from per-tensor
``shard_tensor`` annotations (SPMD rule inference + reshard pass +
cost model).  Here planning IS XLA SPMD: the Engine builds one
DistributedRunner over the annotated ProcessMesh and jits the whole
step; sharding propagation and collective insertion happen in the
compiler (scaling-book recipe: annotate → let XLA insert collectives).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np
import jax

from ...tensor import Tensor
from ...nn.layer import Layer
from .. import collective as coll
from ..runner import DistributedRunner
from .api import ProcessMesh


def _mesh_from_annotations(model: Layer) -> Optional[ProcessMesh]:
    for p in model.parameters():
        pm = getattr(p, "process_mesh", None)
        if pm is not None:
            return pm
    return None


class Engine:
    """auto_parallel.Engine: prepare/fit/evaluate/predict over an
    annotated model."""

    def __init__(self, model: Layer, loss=None, optimizer=None,
                 metrics=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics if isinstance(metrics, (list, tuple)) \
            else ([metrics] if metrics else [])
        self._strategy = strategy
        self._runner: Optional[DistributedRunner] = None
        self._mesh = None

    # -- planning -----------------------------------------------------------
    def _resolve_mesh(self):
        if self._mesh is not None:
            return self._mesh
        pm = _mesh_from_annotations(self._model)
        if pm is not None:
            jmesh = pm.get_jax_mesh()
        else:
            hybrid = getattr(self._strategy, "hybrid_configs", None) or {}
            axes = {k[:-7]: v for k, v in hybrid.items()
                    if k.endswith("_degree") and v and v > 1}
            jmesh = coll.build_mesh(axes)
        self._mesh = jmesh
        return jmesh

    def plan(self, tokens_per_step: int, mp_axis: str = "mp",
             dcn_axes=(), mesh_info=None):
        """Run the SPMD-rule/cost-model placement planner BEFORE the
        first step: profitable Linear pairs get Megatron col/row
        ``dist_spec`` annotations which the runner then realises.
        Returns the per-pair costing decisions (see planner.PlanEntry).
        Must be called before fit/evaluate/predict compile the step."""
        if self._runner is not None:
            raise RuntimeError(
                "Engine.plan must run before the step is compiled; "
                "create a fresh Engine to re-plan")
        from .cost_model import MeshCostInfo
        from .planner import plan_tensor_parallel
        jmesh = self._resolve_mesh()
        info = mesh_info or MeshCostInfo(axis_sizes=dict(jmesh.shape),
                                         dcn_axes=tuple(dcn_axes))
        return plan_tensor_parallel(self._model, info, tokens_per_step,
                                    mp_axis=mp_axis)

    def plan_auto(self, tokens_per_step: int, hbm_bytes: float = 16e9,
                  dcn_axes=(), mesh_info=None):
        """Whole-model planning (upstream parallel-tuner entry): tp
        where priced in, plus the lowest ZeRO stage whose per-device
        footprint fits ``hbm_bytes``.  The chosen stage feeds the
        runner built by the next fit/evaluate/predict call.  Returns
        the ModelPlan for inspection."""
        if self._runner is not None:
            raise RuntimeError(
                "Engine.plan_auto must run before the step is "
                "compiled; create a fresh Engine to re-plan")
        from .cost_model import MeshCostInfo
        from .planner import plan_model
        jmesh = self._resolve_mesh()
        info = mesh_info or MeshCostInfo(axis_sizes=dict(jmesh.shape),
                                         dcn_axes=tuple(dcn_axes))
        self._planned = plan_model(self._model, info, tokens_per_step,
                                   hbm_bytes=hbm_bytes)
        return self._planned

    def tune(self, tokens_per_step: int, n_devices: Optional[int] = None,
             hbm_bytes: float = 16e9, apply: bool = False, **kwargs):
        """Parallel-strategy search (upstream parallel tuner): enumerate
        dp*mp*pp factorizations of ``n_devices`` (default: all visible
        devices), price each with the cost model, rank by step time.
        With ``apply=True`` the winning candidate's degrees become this
        Engine's mesh (must run before the step compiles).  Returns the
        ranked candidate list either way."""
        from .tuner import tune as _tune
        if n_devices is None:
            n_devices = len(jax.devices())
        cands = _tune(self._model, tokens_per_step, n_devices,
                      hbm_bytes=hbm_bytes, **kwargs)
        if apply:
            if self._runner is not None:
                raise RuntimeError(
                    "Engine.tune(apply=True) must run before the step "
                    "is compiled; create a fresh Engine to re-tune")
            best = next((c for c in cands if c.fits), None)
            if best is None:
                raise RuntimeError(
                    "no candidate strategy fits the HBM budget: "
                    + (cands[0].note if cands else "no candidates"))
            axes = {k[:-7]: v for k, v in best.degrees.items()
                    if k.endswith("_degree") and v > 1}
            self._mesh = coll.build_mesh(axes)
            self._tuned = best
        return cands

    def _ensure_runner(self):
        if self._runner is not None:
            return
        jmesh = self._resolve_mesh()
        sharding_stage = 0
        if self._strategy is not None and \
                getattr(self._strategy, "sharding", False):
            sharding_stage = (getattr(self._strategy, "sharding_configs",
                                      None) or {}).get("stage", 2)
        elif getattr(self, "_planned", None) is not None:
            sharding_stage = self._planned.sharding_stage
        elif getattr(self, "_tuned", None) is not None:
            sharding_stage = self._tuned.sharding_stage
        self._runner = DistributedRunner(
            self._model, self._optimizer, self._loss, mesh=jmesh,
            sharding_stage=sharding_stage)

    # -- train loop ---------------------------------------------------------
    def fit(self, train_data, epochs: int = 1, batch_size: int = 1,
            steps_per_epoch: Optional[int] = None, verbose: int = 1,
            log_freq: int = 10):
        from ...io import DataLoader
        from ...io.dataset import Dataset
        self._ensure_runner()
        loader = train_data if not isinstance(train_data, Dataset) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=True)
        history = {"loss": []}
        for epoch in range(epochs):
            loss = None
            for step, batch in enumerate(loader):
                if steps_per_epoch and step >= steps_per_epoch:
                    break
                inputs, labels = self._split_batch(batch)
                loss = self._runner.train_step(inputs, labels)
                if verbose and step % log_freq == 0:
                    print(f"epoch {epoch} step {step} "
                          f"loss {float(np.asarray(loss)):.4f}")
            if loss is None:
                raise ValueError("Engine.fit consumed no batches "
                                 "(empty DataLoader)")
            history["loss"].append(float(np.asarray(loss)))
        return history

    def evaluate(self, eval_data, batch_size: int = 1, verbose: int = 0):
        from ...io import DataLoader
        from ...io.dataset import Dataset
        self._ensure_runner()
        loader = eval_data if not isinstance(eval_data, Dataset) else \
            DataLoader(eval_data, batch_size=batch_size)
        losses = []
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            losses.append(float(np.asarray(
                self._runner.eval_step(inputs, labels))))
        out = {"loss": float(np.mean(losses)) if losses else None}
        if verbose:
            print(f"eval loss {out['loss']}")
        return out

    def predict(self, test_data, batch_size: int = 1):
        from ...io import DataLoader
        from ...io.dataset import Dataset
        self._ensure_runner()
        loader = test_data if not isinstance(test_data, Dataset) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch, labeled=False)
            outs.append(self._runner.predict_step(inputs))
        return outs

    @staticmethod
    def _split_batch(batch, labeled=True):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                # trailing element is the label; predict drops it
                # (hapi convention for datasets that carry labels)
                return list(batch[:-1]), ([batch[-1]] if labeled else [])
            return list(batch), []
        return [batch], []

    # -- io -----------------------------------------------------------------
    def save(self, path: str):
        from ...framework.io import save
        save({"model": self._model.state_dict(),
              "optimizer": (self._optimizer.state_dict()
                            if self._optimizer else {})}, path)

    def load(self, path: str):
        from ...framework.io import load
        state = load(path)
        self._model.set_state_dict(state["model"])
        if self._optimizer and state.get("optimizer"):
            self._optimizer.set_state_dict(state["optimizer"])

    @property
    def main_program(self):  # static-graph parity shim
        return None


class DistModel:
    """Result of dist.to_static: call it with a batch to run one
    compiled train/eval step (upstream DistModel semantics)."""

    def __init__(self, layer, loader, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self._engine = Engine(layer, loss, optimizer, metrics, strategy)
        self._engine._ensure_runner()
        self._mode = "train" if optimizer is not None else "eval"
        self.network = layer

    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def __call__(self, *args):
        if len(args) >= 2:
            inputs, labels = list(args[:-1]), [args[-1]]
        else:
            inputs, labels = list(args), []
        r = self._engine._runner
        if self._mode == "train":
            return Tensor(r.train_step(inputs, labels))
        return Tensor(r.eval_step(inputs, labels))

    def state_dict(self):
        return self.network.state_dict()

    def dist_main_program(self, mode=None):
        return None


def to_static(layer: Layer, loader=None, loss=None, optimizer=None,
              strategy=None) -> DistModel:
    return DistModel(layer, loader, loss, optimizer, strategy)
