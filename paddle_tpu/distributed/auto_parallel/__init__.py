from .api import (  # noqa
    ProcessMesh, shard_tensor, shard_op, dtensor_from_fn, reshard,
    shard_dataloader, Placement, Replicate, Shard, Partial)
from .engine import Engine, DistModel, to_static  # noqa
