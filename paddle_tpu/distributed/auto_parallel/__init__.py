from .api import (  # noqa
    ProcessMesh, shard_tensor, shard_op, dtensor_from_fn, reshard,
    shard_dataloader, Placement, Replicate, Shard, Partial)
from .engine import Engine, DistModel, to_static  # noqa
from .spmd_rules import DistSpec, infer_forward, replicated  # noqa
from .cost_model import (  # noqa
    MeshCostInfo, AxisLink, CommOpCost, reshard_cost, all_reduce_cost,
    all_gather_cost, reduce_scatter_cost, all_to_all_cost, p2p_cost)
from .planner import plan_tensor_parallel, PlanEntry  # noqa
from .tuner import (  # noqa
    ModelStats, Candidate, model_stats, tune_strategy, tune)
