from .api import (  # noqa
    ProcessMesh, shard_tensor, shard_op, dtensor_from_fn, reshard)
