"""Placement planner: SPMD rules + cost model → parameter dist specs.

Parity: the planning half of upstream auto_parallel (completer +
parallel tuner) reduced to its load-bearing decision for dense
transformer/MLP models: WHICH weight matrices to tensor-shard on the
'mp' axis.  Upstream reaches the same placement through SPMD-rule
completion + cost comparison; this planner prices the two candidate
plans directly with the cost model:

* replicated: no comm, every rank does the full matmul pair;
* Megatron col→row pair: each rank does 1/mp of the FLOPs, one
  all-reduce of the pair's output activation per fwd (and one in bwd).

The tp plan wins when the per-step matmul time saved exceeds the
all-reduce cost — exactly the tradeoff the cost model exists to price.
Placements are written as ``dist_spec`` annotations, which
DistributedRunner/XLA then realise (collectives emitted by SPMD
propagation).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ...nn.layer import Layer
from .cost_model import (MeshCostInfo, all_gather_cost,
                         all_reduce_cost)

# practical bf16 matmul throughput to price FLOP savings against
# (v5e-class; ranking-only, same caveat as the comm numbers)
_MATMUL_FLOPS_PER_US = 160e6


@dataclass
class PlanEntry:
    first: Layer                     # column-sharded linear
    second: Layer                    # row-sharded linear
    saved_us: float                  # matmul time saved per step
    comm_us: float                   # all-reduce cost per step
    applied: bool = False


def _linear_chains(model: Layer) -> List[Tuple[Layer, Layer]]:
    """Consecutive Linear pairs A[in,h] → B[h,out] inside each
    container, the col→row tp pattern (attention qkv/proj and MLP
    fc1/fc2 both have this shape).  Strictly ``nn.Linear``: an
    Embedding also carries a 2-D weight but is a gather, not a matmul,
    and must not be priced as one."""
    pairs = []
    from ...nn.common import Linear

    def _ours(lin):
        # unannotated, or annotated BY A PREVIOUS PLANNER RUN (marked
        # _auto_planned) — keeps re-planning idempotent while never
        # touching user-placed weights
        spec = getattr(lin.weight, "dist_spec", None)
        return spec is None or getattr(lin.weight, "_auto_planned",
                                       False)

    def walk(layer):
        lins = []
        for child in layer.children():
            if isinstance(child, Linear) and _ours(child):
                lins.append(child)
            elif not list(child.parameters()):
                continue   # activation/dropout: chain-transparent
            else:
                if lins:
                    _pair(lins)
                    lins = []
                walk(child)
        if lins:
            _pair(lins)

    def _pair(lins):
        # pair only a strict expand→contract shape signature
        # (a: in<out, b: in>out, a.out == b.in — the MLP/ffn pattern).
        # Definition-order adjacency alone mispairs parallel
        # projections: q/k/v/out in an attention block are consecutive
        # same-shaped Linears with NO dataflow between them, and square
        # chains are therefore skipped (conservative by design).
        i = 0
        while i + 1 < len(lins):
            a, b = lins[i], lins[i + 1]
            a_in, a_out = a.weight.shape
            b_in, b_out = b.weight.shape
            if a_out == b_in and a_in < a_out and b_in > b_out:
                pairs.append((a, b))
                i += 2
            else:
                i += 1

    walk(model)
    return pairs


def plan_tensor_parallel(model: Layer, mesh: MeshCostInfo,
                         tokens_per_step: int,
                         mp_axis: str = "mp",
                         dtype="bfloat16") -> List[PlanEntry]:
    """Annotate profitable Linear pairs with Megatron col/row specs.

    ``tokens_per_step`` is the activation row count (batch × seq) the
    plan is priced at.  Returns the per-pair decisions (applied or not)
    so callers/tests can inspect the costing.
    """
    mp = mesh.size(mp_axis)
    entries: List[PlanEntry] = []
    if mp <= 1:
        return entries
    itemsize = np.dtype(dtype).itemsize
    for a, b in _linear_chains(model):
        k_in, h = a.weight.shape
        _, n_out = b.weight.shape
        # fwd+bwd matmul time saved: 3 passes (fwd, dgrad, wgrad) of
        # the pair's 2 matmuls, each cut to 1/mp
        flops = 3.0 * 2.0 * tokens_per_step * h * (k_in + n_out)
        saved = flops * (1 - 1.0 / mp) / _MATMUL_FLOPS_PER_US
        # fwd all-reduces the pair OUTPUT [T, n_out]; bwd all-reduces
        # the INPUT gradient [T, k_in] (the mirror-image collective)
        comm = (all_reduce_cost(
                    float(tokens_per_step) * n_out * itemsize,
                    mp_axis, mesh)
                + all_reduce_cost(
                    float(tokens_per_step) * k_in * itemsize,
                    mp_axis, mesh))
        e = PlanEntry(a, b, saved, comm)
        already = (getattr(a.weight, "dist_spec", None) is not None
                   and getattr(b.weight, "dist_spec", None) is not None)
        if saved > comm or already:
            a.weight.dist_spec = (None, mp_axis)
            a.weight._auto_planned = True
            if getattr(a, "bias", None) is not None:
                a.bias.dist_spec = (mp_axis,)
                a.bias._auto_planned = True
            b.weight.dist_spec = (mp_axis, None)
            b.weight._auto_planned = True
            e.applied = True
        entries.append(e)
    return entries


# ---------------------------------------------------------------------------
# whole-model planning (dp + ZeRO stage by memory + tp where priced in)
# ---------------------------------------------------------------------------

@dataclass
class ModelPlan:
    """Decisions for an arbitrary model (upstream parallel-tuner
    output, reduced to the load-bearing choices)."""

    dp_degree: int
    sharding_stage: int              # 0..3 (0 = pure dp)
    sharding_degree: int
    tp_entries: List[PlanEntry]
    param_bytes: float               # per-replica, after tp
    mem_bytes: float                 # est. per-device params+grads+opt
    extra_comm_us: float             # stage-3 per-step all-gather price
    reason: str = ""


def _model_param_bytes(model: Layer, mp: int, dtype) -> float:
    """Per-replica parameter bytes with tp-sharded weights divided."""
    itemsize = np.dtype(dtype).itemsize if dtype != "bfloat16" else 2
    total = 0.0
    for p in model.parameters():
        n = float(np.prod(p.shape)) * itemsize
        spec = getattr(p, "dist_spec", None)
        if spec is not None and mp > 1:
            # divide only when the spec actually shards on the mp axis
            axes = set()
            for d in spec:
                if isinstance(d, (list, tuple)):
                    axes.update(d)
                elif d is not None:
                    axes.add(d)
            if "mp" in axes:
                n /= mp
        total += n
    return total


def plan_model(model: Layer, mesh: MeshCostInfo, tokens_per_step: int,
               hbm_bytes: float = 16e9, dp_axis: str = "dp",
               sharding_axis: str = "sharding", mp_axis: str = "mp",
               dtype="bfloat16",
               optimizer_bytes_per_param: float = 12.0) -> ModelPlan:
    """Plan ANY model: tp where the cost model prices it in (transformer
    matmul chains; conv nets simply get no profitable pairs), dp on the
    batch, and the LOWEST ZeRO stage whose per-device footprint fits
    ``hbm_bytes`` (upstream sharding-stage selection logic; stage 3's
    per-step parameter all-gather is priced and reported).

    ``optimizer_bytes_per_param``: 12 = Adam-class fp32 master + two
    moments per bf16 param."""
    tp_entries = plan_tensor_parallel(model, mesh, tokens_per_step,
                                      mp_axis, dtype=dtype)
    mp = mesh.size(mp_axis)
    P = _model_param_bytes(model, mp, dtype)
    S = max(mesh.size(sharding_axis), 1)
    grad_b = P                       # grads in param dtype
    opt_b = (P / 2.0) * optimizer_bytes_per_param \
        if dtype == "bfloat16" else P * 3.0
    stages = {
        0: P + grad_b + opt_b,
        1: P + grad_b + opt_b / S,
        2: P + grad_b / S + opt_b / S,
        3: P / S + grad_b / S + opt_b / S,
    }
    stage = 0
    for st in (0, 1, 2, 3):
        stage = st
        if stages[st] <= hbm_bytes:
            break
    if S <= 1:
        stage = 0
    extra = 0.0
    if stage == 3:
        # stage-3 re-gathers the sharded params every step (fwd+bwd)
        extra = 2.0 * all_gather_cost(P, sharding_axis, mesh)
    reason = (f"stage {stage}: per-device "
              f"{stages[stage] / 1e9:.2f} GB vs budget "
              f"{hbm_bytes / 1e9:.2f} GB"
              + ("; WARNING: stage 3 still over budget"
                 if stages[stage] > hbm_bytes else ""))
    return ModelPlan(
        dp_degree=mesh.size(dp_axis), sharding_stage=stage,
        sharding_degree=S, tp_entries=tp_entries, param_bytes=P,
        mem_bytes=stages[stage], extra_comm_us=extra, reason=reason)
