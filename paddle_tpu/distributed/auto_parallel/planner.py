"""Placement planner: SPMD rules + cost model → parameter dist specs.

Parity: the planning half of upstream auto_parallel (completer +
parallel tuner) reduced to its load-bearing decision for dense
transformer/MLP models: WHICH weight matrices to tensor-shard on the
'mp' axis.  Upstream reaches the same placement through SPMD-rule
completion + cost comparison; this planner prices the two candidate
plans directly with the cost model:

* replicated: no comm, every rank does the full matmul pair;
* Megatron col→row pair: each rank does 1/mp of the FLOPs, one
  all-reduce of the pair's output activation per fwd (and one in bwd).

The tp plan wins when the per-step matmul time saved exceeds the
all-reduce cost — exactly the tradeoff the cost model exists to price.
Placements are written as ``dist_spec`` annotations, which
DistributedRunner/XLA then realise (collectives emitted by SPMD
propagation).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ...nn.layer import Layer
from .cost_model import MeshCostInfo, all_reduce_cost

# practical bf16 matmul throughput to price FLOP savings against
# (v5e-class; ranking-only, same caveat as the comm numbers)
_MATMUL_FLOPS_PER_US = 160e6


@dataclass
class PlanEntry:
    first: Layer                     # column-sharded linear
    second: Layer                    # row-sharded linear
    saved_us: float                  # matmul time saved per step
    comm_us: float                   # all-reduce cost per step
    applied: bool = False


def _linear_chains(model: Layer) -> List[Tuple[Layer, Layer]]:
    """Consecutive Linear pairs A[in,h] → B[h,out] inside each
    container, the col→row tp pattern (attention qkv/proj and MLP
    fc1/fc2 both have this shape).  Strictly ``nn.Linear``: an
    Embedding also carries a 2-D weight but is a gather, not a matmul,
    and must not be priced as one."""
    pairs = []
    from ...nn.common import Linear

    def walk(layer):
        lins = []
        for child in layer.children():
            if isinstance(child, Linear) and \
                    getattr(child.weight, "dist_spec", None) is None:
                lins.append(child)
            elif not list(child.parameters()):
                continue   # activation/dropout: chain-transparent
            else:
                if lins:
                    _pair(lins)
                    lins = []
                walk(child)
        if lins:
            _pair(lins)

    def _pair(lins):
        # pair only a strict expand→contract shape signature
        # (a: in<out, b: in>out, a.out == b.in — the MLP/ffn pattern).
        # Definition-order adjacency alone mispairs parallel
        # projections: q/k/v/out in an attention block are consecutive
        # same-shaped Linears with NO dataflow between them, and square
        # chains are therefore skipped (conservative by design).
        i = 0
        while i + 1 < len(lins):
            a, b = lins[i], lins[i + 1]
            a_in, a_out = a.weight.shape
            b_in, b_out = b.weight.shape
            if a_out == b_in and a_in < a_out and b_in > b_out:
                pairs.append((a, b))
                i += 2
            else:
                i += 1

    walk(model)
    return pairs


def plan_tensor_parallel(model: Layer, mesh: MeshCostInfo,
                         tokens_per_step: int,
                         mp_axis: str = "mp",
                         dtype="bfloat16") -> List[PlanEntry]:
    """Annotate profitable Linear pairs with Megatron col/row specs.

    ``tokens_per_step`` is the activation row count (batch × seq) the
    plan is priced at.  Returns the per-pair decisions (applied or not)
    so callers/tests can inspect the costing.
    """
    mp = mesh.size(mp_axis)
    entries: List[PlanEntry] = []
    if mp <= 1:
        return entries
    itemsize = np.dtype(dtype).itemsize
    for a, b in _linear_chains(model):
        k_in, h = a.weight.shape
        _, n_out = b.weight.shape
        # fwd+bwd matmul time saved: 3 passes (fwd, dgrad, wgrad) of
        # the pair's 2 matmuls, each cut to 1/mp
        flops = 3.0 * 2.0 * tokens_per_step * h * (k_in + n_out)
        saved = flops * (1 - 1.0 / mp) / _MATMUL_FLOPS_PER_US
        # fwd all-reduces the pair OUTPUT [T, n_out]; bwd all-reduces
        # the INPUT gradient [T, k_in] (the mirror-image collective)
        comm = (all_reduce_cost(
                    float(tokens_per_step) * n_out * itemsize,
                    mp_axis, mesh)
                + all_reduce_cost(
                    float(tokens_per_step) * k_in * itemsize,
                    mp_axis, mesh))
        e = PlanEntry(a, b, saved, comm)
        if saved > comm:
            a.weight.dist_spec = (None, mp_axis)
            if getattr(a, "bias", None) is not None:
                a.bias.dist_spec = (mp_axis,)
            b.weight.dist_spec = (mp_axis, None)
            e.applied = True
        entries.append(e)
    return entries
