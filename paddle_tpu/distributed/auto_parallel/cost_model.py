"""Communication / reshard cost model for the auto-parallel planner.

Parity: upstream's cost model under auto_parallel (comm+comp op costs
feeding the planner — SURVEY.md §2.2 "Auto-parallel (semi-auto)": cost
model).  Upstream prices NCCL collectives per cluster topology; the
TPU-native version prices XLA collectives per mesh AXIS, distinguishing
ICI (intra-slice torus links) from DCN (inter-slice) — the distinction
that decides which axes should carry mp/sep vs dp/pp in a multi-slice
mesh (SURVEY.md §5.8).

All costs are alpha-beta estimates in microseconds:
``t = alpha * steps + bytes_on_wire / bandwidth``.  They are meant for
RANKING placements, not for wall-clock prediction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np

from .spmd_rules import DistSpec

# v5e-class defaults (per-direction, per-link): ICI ~4.5e10 B/s and
# ~1 us hop latency; DCN ~2.5e9 B/s and ~10 us.  Override per axis via
# MeshCostInfo.
_ICI_BW = 45e9
_DCN_BW = 2.5e9
_ICI_ALPHA_US = 1.0
_DCN_ALPHA_US = 10.0


@dataclass
class AxisLink:
    bandwidth: float
    alpha_us: float

    @classmethod
    def ici(cls):
        return cls(_ICI_BW, _ICI_ALPHA_US)

    @classmethod
    def dcn(cls):
        return cls(_DCN_BW, _DCN_ALPHA_US)


@dataclass
class MeshCostInfo:
    """Mesh axis sizes + link class per axis.  By convention dp/pp-outer
    axes ride DCN on multi-slice deployments; everything else ICI."""

    axis_sizes: Dict[str, int]
    links: Dict[str, AxisLink] = field(default_factory=dict)
    dcn_axes: Sequence[str] = ()

    def link(self, axis: str) -> AxisLink:
        if axis in self.links:
            return self.links[axis]
        return AxisLink.dcn() if axis in self.dcn_axes else AxisLink.ici()

    def size(self, axis) -> int:
        if isinstance(axis, tuple):
            n = 1
            for a in axis:
                n *= self.axis_sizes.get(a, 1)
            return n
        return self.axis_sizes.get(axis, 1)


def _bytes(shape: Sequence[int], dtype) -> float:
    return float(np.prod(shape)) * np.dtype(dtype).itemsize


def _ring_cost(nbytes: float, n: int, link: AxisLink,
               steps_factor: float) -> float:
    """Bandwidth-optimal ring collective: (n-1)/n of the data crosses
    each link, ``steps_factor``×(n-1) latency hops."""
    if n <= 1:
        return 0.0
    return (link.alpha_us * steps_factor * (n - 1)
            + (nbytes * (n - 1) / n) / link.bandwidth * 1e6)


def _axis_link(axis, mesh: MeshCostInfo) -> AxisLink:
    """Link class for a (possibly multi-axis) collective: the SLOWEST
    member link bounds the ring — one DCN axis makes it a DCN ring."""
    axes = axis if isinstance(axis, tuple) else (axis,)
    links = [mesh.link(a) for a in axes]
    return min(links, key=lambda l: l.bandwidth)


def all_reduce_cost(nbytes, axis, mesh: MeshCostInfo) -> float:
    # reduce-scatter + all-gather
    return _ring_cost(nbytes, mesh.size(axis), _axis_link(axis, mesh),
                      2.0)


def all_gather_cost(nbytes, axis, mesh: MeshCostInfo) -> float:
    """``nbytes`` = FULL (gathered) size."""
    return _ring_cost(nbytes, mesh.size(axis), _axis_link(axis, mesh),
                      1.0)


def reduce_scatter_cost(nbytes, axis, mesh: MeshCostInfo) -> float:
    return _ring_cost(nbytes, mesh.size(axis), _axis_link(axis, mesh),
                      1.0)


def all_to_all_cost(nbytes, axis, mesh: MeshCostInfo) -> float:
    n = mesh.size(axis)
    link = _axis_link(axis, mesh)
    if n <= 1:
        return 0.0
    return (link.alpha_us * (n - 1)
            + (nbytes * (n - 1) / n / n) / link.bandwidth * 1e6)


def p2p_cost(nbytes, axis, mesh: MeshCostInfo) -> float:
    link = mesh.link(axis)
    return link.alpha_us + nbytes / link.bandwidth * 1e6


def reshard_cost(src: DistSpec, dst: DistSpec, shape: Sequence[int],
                 dtype, mesh: MeshCostInfo) -> float:
    """Price moving one tensor ``src`` → ``dst``.

    Decomposed per upstream's reshard planner into the three primitive
    transitions, priced at the FULL tensor size divided by what stays
    sharded:

    * partial → settled: all-reduce over the partial axes (or
      reduce-scatter when the destination shards a dim on that axis);
    * sharded dim → replicated/resharded: all-gather over the axes
      leaving the dim;
    * replicated → sharded: free (local slice).
    """
    if src == dst:
        return 0.0
    full = _bytes(shape, dtype)
    cost = 0.0
    # every axis currently sharding a dim of src divides the bytes a
    # rank holds — collectives are priced at that LOCAL size (pricing
    # at full size inflated mp-sharded settles by the mp factor)
    src_shard_axes = set()
    for i in range(src.ndim):
        src_shard_axes.update(src.axes_of(i))

    def _local(nb, axes_set):
        n = 1
        for a in axes_set:
            n *= mesh.size(a)
        return nb / max(n, 1)

    # 1. settle partials (tensor still sharded by all src dim axes)
    for ax in src.partial - dst.partial:
        dst_scatter = any(ax in dst.axes_of(i)
                          for i in range(dst.ndim))
        nb = _local(full, src_shard_axes)
        if dst_scatter:
            cost += reduce_scatter_cost(nb, ax, mesh)
        else:
            cost += all_reduce_cost(nb, ax, mesh)
    # 2. gather dims whose axes leave: the gather of ``ax`` produces
    # bytes = full over whatever OTHER axes still shard the tensor
    for i in range(src.ndim):
        leaving = set(src.axes_of(i)) - (set(dst.axes_of(i))
                                         if i < dst.ndim else set())
        for ax in leaving:
            cost += all_gather_cost(
                _local(full, src_shard_axes - {ax}), ax, mesh)
    # 3. replicated → sharded: local slice, free
    return cost


@dataclass
class CommOpCost:
    """Named entry mirroring upstream's per-collective cost classes."""

    op: str
    nbytes: float
    axis: object
    mesh: MeshCostInfo

    _FNS = {
        "all_reduce": all_reduce_cost,
        "all_gather": all_gather_cost,
        "reduce_scatter": reduce_scatter_cost,
        "all_to_all": all_to_all_cost,
        "p2p": p2p_cost,
    }

    def time_us(self) -> float:
        return self._FNS[self.op](self.nbytes, self.axis, self.mesh)
