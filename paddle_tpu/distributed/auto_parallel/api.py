"""Semi-auto parallel API (parity: python/paddle/distributed/
auto_parallel/ — ProcessMesh, shard_tensor; SURVEY.md §2.2 "Auto-parallel
(semi-auto)": Paddle's own GSPMD analog).

On TPU this is nearly definitional: ProcessMesh IS jax.sharding.Mesh,
shard_tensor IS device_put with a NamedSharding, and "SPMD rule
inference + reshard" IS the XLA SPMD partitioner.  The API therefore
maps 1:1 with no pass pipeline to port.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...tensor import Tensor


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    def __repr__(self):
        return "Partial()"


class ProcessMesh:
    def __init__(self, mesh: Union[List, np.ndarray],
                 dim_names: Optional[List[str]] = None):
        self._arr = np.asarray(mesh)
        self.dim_names = dim_names or [f"d{i}"
                                       for i in range(self._arr.ndim)]
        self.shape = list(self._arr.shape)
        self.process_ids = self._arr.reshape(-1).tolist()
        self._jax_mesh = None

    def get_jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devices = jax.devices()
            picked = np.asarray([devices[i % len(devices)]
                                 for i in self.process_ids]).reshape(
                self._arr.shape)
            self._jax_mesh = Mesh(picked, tuple(self.dim_names))
        return self._jax_mesh

    @property
    def mesh(self):
        return self._arr

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._arr, other._arr) and \
            self.dim_names == other.dim_names

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dims={self.dim_names})"


def _placements_to_spec(placements: Sequence[Placement], mesh: ProcessMesh,
                        ndim: int) -> PartitionSpec:
    spec: List = [None] * ndim
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            spec[p.dim] = mesh.dim_names[mesh_dim]
    return PartitionSpec(*spec)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Annotate + place a tensor on the mesh.  A Tensor/Parameter input
    is annotated IN PLACE (and returned), so module-registered
    parameters keep their registration — the natural way to annotate a
    model before handing it to auto_parallel.Engine."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    jmesh = mesh.get_jax_mesh()
    spec = _placements_to_spec(placements, mesh, t.ndim)
    t._value = jax.device_put(t._value, NamedSharding(jmesh, spec))
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    t.dist_spec = tuple(spec)
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """Move a tensor to a new placement (upstream dist.reshard).

    Eagerly this is a device_put (XLA emits the collective/resharding
    transfer); under a jit trace it lowers to a sharding constraint so
    the SPMD partitioner plans the reshard inside the step.  ``Partial``
    placements are accepted for annotation parity but have no eager
    value representation — resharding Partial→Replicate is the SPMD
    partitioner's psum and only meaningful inside a traced program.
    """
    val = x._value if isinstance(x, Tensor) else x
    jmesh = mesh.get_jax_mesh()
    ndim = getattr(val, "ndim", Tensor(val).ndim)
    spec = _placements_to_spec(placements, mesh, ndim)
    if isinstance(val, jax.core.Tracer):
        new_val = jax.lax.with_sharding_constraint(
            val, NamedSharding(jmesh, spec))
    else:
        new_val = jax.device_put(val, NamedSharding(jmesh, spec))
    # a NEW tensor (upstream dist.reshard semantics): the input keeps
    # its placement — reshard-for-a-read must not re-place the caller's
    # parameter in place
    out = Tensor(new_val)
    if isinstance(x, Tensor):
        out.stop_gradient = x.stop_gradient
    out.dist_spec = tuple(spec)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def shard_op(op, mesh: ProcessMesh = None, in_placements=None,
             out_placements=None):
    """Wrap an op so its inputs/outputs carry the given placements —
    the manual escape hatch of upstream's semi-auto SPMD rules."""
    def wrapper(*args, **kwargs):
        if mesh is not None and in_placements:
            ip = list(in_placements)
            if ip and isinstance(ip[0], Placement):
                ip = [ip]          # flat form = placements for arg 0
            args = tuple(
                reshard(a, mesh, pl) if isinstance(a, Tensor) and pl
                else a
                for a, pl in zip(args, ip + [None] * (len(args)
                                                      - len(ip))))
        out = op(*args, **kwargs)
        if mesh is not None and out_placements:
            if isinstance(out, (list, tuple)):
                outs = [reshard(o, mesh, pl) if pl else o
                        for o, pl in zip(out, out_placements)]
                return type(out)(outs)
            return reshard(out, mesh, out_placements[0]
                           if isinstance(out_placements[0],
                                         (list, tuple))
                           else out_placements)
        return out
    return wrapper


def shard_dataloader(dataloader, meshes, input_keys=None,
                     shard_dims="dp"):
    """Wrap a DataLoader so every yielded batch is placed batch-sharded
    on the data axis of the mesh (upstream dist.shard_dataloader).
    Dict batches are supported via their keys (``input_keys`` restricts
    which entries get sharded)."""
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes
    if isinstance(shard_dims, int):
        shard_dims = mesh.dim_names[shard_dims]
    if not isinstance(shard_dims, str):
        raise NotImplementedError(
            "per-input shard_dims lists are not supported; pass one "
            "mesh dim name (str) or index (int)")
    dim = shard_dims
    axis_size = int(dict(zip(mesh.dim_names, mesh.shape))[dim])

    def _place(it, sh):
        t = it if isinstance(it, Tensor) else Tensor(np.asarray(it))
        if t.shape and t.shape[0] % axis_size != 0:
            raise ValueError(
                f"shard_dataloader: batch dim {t.shape[0]} not "
                f"divisible by mesh axis {dim!r} ({axis_size}); use "
                "drop_last=True or a divisible batch size")
        t._value = jax.device_put(t._value, sh)
        return t

    class _Sharded:
        def __init__(self, loader):
            self._loader = loader

        def __len__(self):
            return len(self._loader)

        def __iter__(self):
            jmesh = mesh.get_jax_mesh()
            sh = NamedSharding(jmesh, PartitionSpec(dim))
            for batch in self._loader:
                if isinstance(batch, dict):
                    keys = input_keys or list(batch)
                    yield {k: (_place(v, sh) if k in keys else v)
                           for k, v in batch.items()}
                    continue
                items = batch if isinstance(batch, (list, tuple)) \
                    else [batch]
                out = [_place(it, sh) for it in items]
                yield out if isinstance(batch, (list, tuple)) else out[0]

    return _Sharded(dataloader)
