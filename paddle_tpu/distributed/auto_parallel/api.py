"""Semi-auto parallel API (parity: python/paddle/distributed/
auto_parallel/ — ProcessMesh, shard_tensor; SURVEY.md §2.2 "Auto-parallel
(semi-auto)": Paddle's own GSPMD analog).

On TPU this is nearly definitional: ProcessMesh IS jax.sharding.Mesh,
shard_tensor IS device_put with a NamedSharding, and "SPMD rule
inference + reshard" IS the XLA SPMD partitioner.  The API therefore
maps 1:1 with no pass pipeline to port.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...tensor import Tensor


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    def __repr__(self):
        return "Partial()"


class ProcessMesh:
    def __init__(self, mesh: Union[List, np.ndarray],
                 dim_names: Optional[List[str]] = None):
        self._arr = np.asarray(mesh)
        self.dim_names = dim_names or [f"d{i}"
                                       for i in range(self._arr.ndim)]
        self.shape = list(self._arr.shape)
        self.process_ids = self._arr.reshape(-1).tolist()
        self._jax_mesh = None

    def get_jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devices = jax.devices()
            picked = np.asarray([devices[i % len(devices)]
                                 for i in self.process_ids]).reshape(
                self._arr.shape)
            self._jax_mesh = Mesh(picked, tuple(self.dim_names))
        return self._jax_mesh

    @property
    def mesh(self):
        return self._arr

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._arr, other._arr) and \
            self.dim_names == other.dim_names

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dims={self.dim_names})"


def _placements_to_spec(placements: Sequence[Placement], mesh: ProcessMesh,
                        ndim: int) -> PartitionSpec:
    spec: List = [None] * ndim
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            spec[p.dim] = mesh.dim_names[mesh_dim]
    return PartitionSpec(*spec)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Annotate + place a tensor on the mesh.  A Tensor/Parameter input
    is annotated IN PLACE (and returned), so module-registered
    parameters keep their registration — the natural way to annotate a
    model before handing it to auto_parallel.Engine."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    jmesh = mesh.get_jax_mesh()
    spec = _placements_to_spec(placements, mesh, t.ndim)
    t._value = jax.device_put(t._value, NamedSharding(jmesh, spec))
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    t.dist_spec = tuple(spec)
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    return shard_tensor(x, mesh, placements)


def shard_op(op, mesh: ProcessMesh = None, in_placements=None,
             out_placements=None):
    def wrapper(*args, **kwargs):
        return op(*args, **kwargs)
    return wrapper
