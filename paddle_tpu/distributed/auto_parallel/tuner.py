"""Parallel-strategy tuner: search (dp, mp, pp, ZeRO stage) for a model.

Parity: upstream's parallel tuner under auto_parallel
(`python/paddle/distributed/auto_parallel/static/tuner/` —
parallel_tuner + rule_based_tuner: enumerate process-mesh
factorizations, prune by memory, rank by cost model).  The TPU-native
version prices each candidate with the same alpha-beta cost model the
planner uses (`cost_model.py`), with the ICI/DCN axis distinction that
decides multi-slice layouts (SURVEY.md §5.8, DESIGN-DCN.md).

The tuner works on an analytic ``ModelStats`` summary — extracted from
a live ``nn.Layer`` via :func:`model_stats` (no compile, no devices) or
given directly — so searching a 1.3B-param space costs microseconds.

Per-candidate step-time model (decoder-transformer shaped; conv nets
degenerate to the dp-only row, matching ``plan_model``'s behavior):

* compute: ``6 * P * T`` FLOPs per step (fwd + bwd), split over all
  devices, inflated by the pipeline bubble ``(M + pp - 1) / M``;
* mp: 4 all-reduces per layer per microbatch of the activation slab
  (Megatron col->row pairs, fwd + bwd);
* pp: one activation p2p per stage boundary per microbatch direction;
* dp: one fused gradient all-reduce of the per-device shard (f32 wire
  by default — `compressed.py` int8 is priced by passing
  ``dp_wire_bytes``), of which ``dp_overlap`` hides under backward
  (XLA latency-hiding scheduler; same 0.7 default the validated
  scaling projection uses — DESIGN-DCN.md);
* sharding stage chosen per-candidate exactly like ``plan_model``
  (lowest stage that fits), stage-3 re-gather priced in.

Returned candidates are ranked by estimated step time among those that
fit HBM; non-fitting candidates are kept (flagged) so callers can see
WHY a layout was rejected — the same observability upstream's tuner
logs provide.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from .cost_model import (MeshCostInfo, AxisLink, all_gather_cost,
                         all_reduce_cost, p2p_cost)

# practical bf16 matmul throughput used for ranking (same constant the
# planner prices tp against)
_FLOPS_PER_US = 160e6


@dataclass
class ModelStats:
    """Analytic summary of a model for strategy search."""

    total_params: float              # parameter count
    n_layers: int                    # repeated block count (pp cut unit)
    hidden: int                      # activation width
    tokens_per_step: int             # global batch x seq
    layer_params: float = 0.0        # params per repeated block
    head_params: float = 0.0         # embedding/head (first/last stage)
    param_dtype_bytes: float = 2.0   # bf16 storage
    act_bytes_per_token_layer: float = 0.0  # remat'd activation footprint

    def __post_init__(self):
        if self.layer_params == 0.0 and self.n_layers:
            self.layer_params = self.total_params / self.n_layers
        if self.act_bytes_per_token_layer == 0.0:
            # with stage remat only block boundaries are resident:
            # ~2 tensors of width `hidden` in bf16 per layer per token
            self.act_bytes_per_token_layer = 4.0 * self.hidden


@dataclass
class Candidate:
    dp: int
    mp: int
    pp: int
    micro_batches: int
    sharding_stage: int
    step_us: float
    compute_us: float
    mp_comm_us: float
    pp_comm_us: float
    dp_comm_us: float
    mem_bytes: float
    fits: bool
    note: str = ""

    @property
    def degrees(self) -> Dict[str, int]:
        """Hybrid-config degrees.  With ZeRO on, the data-parallel
        ranks ARE the sharding group (upstream convention: dp_degree
        and sharding_degree are separate mesh axes whose sizes
        multiply — ZeRO over all replicas means dp_degree=1,
        sharding_degree=dp)."""
        if self.sharding_stage:
            return {"dp_degree": 1, "mp_degree": self.mp,
                    "pp_degree": self.pp, "sharding_degree": self.dp,
                    "sharding_stage": self.sharding_stage}
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "sharding_degree": 1,
                "sharding_stage": self.sharding_stage}


def model_stats(model, tokens_per_step: int) -> ModelStats:
    """Extract ModelStats from a live Layer: total params, the dominant
    repeated-block family (same class, same param count -> n_layers /
    layer_params), and the widest 2-D weight's width as ``hidden``."""
    total = 0.0
    by_sig: Dict[tuple, List[float]] = {}
    hidden = 0
    for sub in model.sublayers(include_self=False):
        own = [p for p in sub.parameters(include_sublayers=True)]
        if not own:
            continue
        n = float(sum(np.prod(p.shape) for p in own))
        by_sig.setdefault((type(sub).__name__,), []).append(n)
    for p in model.parameters():
        total += float(np.prod(p.shape))
        if len(p.shape) == 2:
            hidden = max(hidden, int(min(p.shape)))
    # dominant family: among repeated equal-param-count classes, the one
    # COVERING the most parameters (count x per-instance params).  Raw
    # count alone would pick inner repeated leaves — e.g. the 4 q/k/v/o
    # Linears inside every attention block outnumber the blocks 4:1 —
    # but the enclosing block family always covers at least as much, so
    # coverage selects the outermost repeat (the true pp cut unit);
    # ties break toward fewer, larger layers.
    best_cov, best_cnt, layer_params = 0.0, 1, total
    for counts in by_sig.values():
        uniq: Dict[float, int] = {}
        for c in counts:
            uniq[c] = uniq.get(c, 0) + 1
        for val, cnt in uniq.items():
            if cnt <= 1 or val <= 0:
                continue
            cov = cnt * val
            if cov > best_cov or (cov == best_cov and val > layer_params):
                best_cov, best_cnt, layer_params = cov, cnt, val
    n_layers = best_cnt
    head = max(total - n_layers * layer_params, 0.0)
    return ModelStats(total_params=total, n_layers=max(n_layers, 1),
                      hidden=max(hidden, 1),
                      tokens_per_step=tokens_per_step,
                      layer_params=layer_params, head_params=head)


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def tune_strategy(stats: ModelStats, n_devices: int,
                  mesh: Optional[MeshCostInfo] = None,
                  hbm_bytes: float = 16e9,
                  micro_batches: int = 8,
                  dp_wire_bytes: float = 4.0,
                  dp_overlap: float = 0.7,
                  optimizer_bytes_per_param: float = 12.0,
                  max_mp: int = 8) -> List[Candidate]:
    """Enumerate dp*mp*pp = n_devices, price each, rank by step time.

    ``mesh``: supplies per-axis link classes; defaults to all-ICI with
    'dp' on DCN only if the caller marks it (multi-slice).  ``max_mp``
    bounds tensor parallel to the intra-host/ICI reach (upstream's
    rule-based tuner applies the same practical bound).
    """
    cands: List[Candidate] = []
    T = float(stats.tokens_per_step)
    P = float(stats.total_params)
    for pp in _divisors(n_devices):
        if pp > stats.n_layers:
            continue
        rest = n_devices // pp
        for mp in _divisors(rest):
            if mp > max_mp or mp > stats.hidden:
                continue
            dp = rest // mp
            m = (MeshCostInfo(
                axis_sizes={"dp": dp, "mp": mp, "pp": pp},
                links=dict(mesh.links) if mesh else {},
                dcn_axes=tuple(mesh.dcn_axes) if mesh else ())
                if mesh is not None else
                MeshCostInfo(axis_sizes={"dp": dp, "mp": mp, "pp": pp}))
            M = micro_batches if pp > 1 else 1
            tokens_micro = T / dp / M

            # --- compute, with pipeline bubble ---
            flops = 6.0 * P * T
            bubble = (M + pp - 1) / M
            compute = flops / n_devices / _FLOPS_PER_US * bubble

            # --- mp comm: 4 AR/layer/microbatch of [tokens_micro, h] ---
            act_bytes = tokens_micro * stats.hidden \
                * stats.param_dtype_bytes
            layers_dev = stats.n_layers / pp
            mp_comm = (4.0 * layers_dev * M
                       * all_reduce_cost(act_bytes, "mp", m)
                       if mp > 1 else 0.0)

            # --- pp comm: 2 directions x (M + pp - 2) boundary p2ps ---
            pp_comm = (2.0 * (M + pp - 2)
                       * p2p_cost(act_bytes, "pp", m)
                       if pp > 1 else 0.0)

            # --- dp comm: fused grad AR of per-device shard, mostly
            # hidden under backward (exposed fraction priced) ---
            grad_bytes = P / mp / pp * dp_wire_bytes
            dp_comm = (all_reduce_cost(grad_bytes, "dp", m)
                       * (1.0 - dp_overlap)) if dp > 1 else 0.0

            # --- memory + ZeRO stage (plan_model's selection logic) ---
            p_dev = P / mp / pp * stats.param_dtype_bytes
            grad_b = p_dev
            opt_b = (p_dev / stats.param_dtype_bytes) \
                * optimizer_bytes_per_param
            S = dp
            act_dev = (tokens_micro * stats.act_bytes_per_token_layer
                       * layers_dev)
            stage_mem = {
                0: p_dev + grad_b + opt_b,
                1: p_dev + grad_b + opt_b / S,
                2: p_dev + grad_b / S + opt_b / S,
                3: p_dev / S + grad_b / S + opt_b / S,
            }
            stage = 0
            for st in (0, 1, 2, 3):
                stage = st
                if stage_mem[st] + act_dev <= hbm_bytes:
                    break
            if S <= 1:
                stage = 0
            mem = stage_mem[stage] + act_dev
            extra = (2.0 * all_gather_cost(p_dev, "dp", m)
                     if stage == 3 else 0.0)

            step = compute + mp_comm + pp_comm + dp_comm + extra
            cands.append(Candidate(
                dp=dp, mp=mp, pp=pp, micro_batches=M,
                sharding_stage=stage, step_us=step, compute_us=compute,
                mp_comm_us=mp_comm, pp_comm_us=pp_comm,
                dp_comm_us=dp_comm, mem_bytes=mem,
                fits=mem <= hbm_bytes,
                note="" if mem <= hbm_bytes else
                f"over budget: {mem / 1e9:.1f} GB > "
                f"{hbm_bytes / 1e9:.1f} GB"))
    cands.sort(key=lambda c: (not c.fits, c.step_us))
    return cands


def tune(model, tokens_per_step: int, n_devices: int,
         **kwargs) -> List[Candidate]:
    """Convenience: extract stats from a Layer and search."""
    return tune_strategy(model_stats(model, tokens_per_step),
                         n_devices, **kwargs)
