"""SPMD inference rules for semi-auto parallel.

Parity: upstream's per-op SPMD rules (paddle/phi/infermeta/spmd_rules/,
exposed through DistAttr inference — SURVEY.md §2.2 "Auto-parallel
(semi-auto)").  Upstream implements one C++ rule per op that maps input
``dims_mapping``s to output dist attrs and flags the reshards needed
when inputs disagree.

TPU-native stance: at RUN time XLA's SPMD partitioner already does this
propagation on the compiled program.  These rules exist for the layer
ABOVE the compiler — the planner: ``Engine``/``shard_op`` use them to
pick placements and to PRICE alternatives (with ``cost_model``) before
anything is compiled, and they are pure shape/spec functions, so the
whole rule set is unit-testable with no devices (the upstream
test/auto_parallel pattern the survey calls out as worth copying).

A placement here is a ``DistSpec``:

* ``dims``: one entry per tensor dim — a mesh axis name, a tuple of
  axis names (multi-axis sharding of one dim), or ``None``
  (replicated dim);
* ``partial``: mesh axes along which the tensor holds partial sums
  (the product of a contraction whose contracted dim was sharded) —
  upstream's ``Partial`` placement.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "DistSpec", "replicated", "infer_forward", "matmul_rule",
    "elementwise_rule", "multiply_rule", "reduction_rule",
    "nonlinear_reduction_rule", "reshape_rule",
    "transpose_rule", "embedding_rule", "softmax_rule", "layer_norm_rule",
    "concat_rule", "split_rule", "flash_attention_rule",
    "cross_entropy_rule", "conv2d_rule", "pool2d_rule",
    "batch_norm_rule",
]


def _norm_dim(entry):
    if entry is None:
        return None
    if isinstance(entry, (list, tuple)):
        t = tuple(entry)
        return t[0] if len(t) == 1 else t
    return entry


@dataclass(frozen=True)
class DistSpec:
    """Sharding of one tensor over a named mesh."""

    dims: Tuple[object, ...]                  # axis | tuple | None per dim
    partial: frozenset = field(default_factory=frozenset)

    def __init__(self, dims: Sequence, partial=()):  # noqa: D401
        object.__setattr__(self, "dims",
                           tuple(_norm_dim(d) for d in dims))
        object.__setattr__(self, "partial", frozenset(partial))

    # -- helpers ----------------------------------------------------------
    @property
    def ndim(self) -> int:
        return len(self.dims)

    def axes_of(self, i: int) -> Tuple[str, ...]:
        d = self.dims[i]
        if d is None:
            return ()
        return d if isinstance(d, tuple) else (d,)

    def used_axes(self) -> frozenset:
        out = set(self.partial)
        for i in range(self.ndim):
            out.update(self.axes_of(i))
        return frozenset(out)

    def with_dim(self, i: int, axis) -> "DistSpec":
        dims = list(self.dims)
        dims[i] = axis
        return DistSpec(dims, self.partial)

    def drop_partial(self) -> "DistSpec":
        return DistSpec(self.dims, ())

    def __repr__(self):
        return f"DistSpec({list(self.dims)!r}, partial={set(self.partial) or '{}'})"


def replicated(ndim: int) -> DistSpec:
    return DistSpec((None,) * ndim)


@dataclass
class RuleResult:
    """Outcome of a rule: the specs each input must be RESHARDED to
    (equal to the given input spec when no reshard is needed), and the
    output spec(s) produced under those input placements."""

    in_specs: List[DistSpec]
    out_specs: List[DistSpec]

    @property
    def out_spec(self) -> DistSpec:
        return self.out_specs[0]

    def reshards(self, given: Sequence[DistSpec]) -> List[int]:
        """Indices of inputs whose placement must change."""
        return [i for i, (a, b) in enumerate(zip(given, self.in_specs))
                if a != b]


def _merge_dim(a, b):
    """Merge one dim's sharding from two operands: equal wins, one-sided
    wins, conflict → replicate (the cheap deterministic resolution
    upstream's rules also use for mismatched dims_mappings)."""
    if a == b:
        return a, False
    if a is None:
        return b, False
    if b is None:
        return a, False
    return None, True


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------
def matmul_rule(x: DistSpec, y: DistSpec, trans_x: bool = False,
                trans_y: bool = False) -> RuleResult:
    """[..., M, K] @ [..., K, N] (modulo transposes).

    Factor sharding (same scheme as GSPMD / upstream matmul.cc):
    batch dims merge elementwise; M comes from x, N from y; a K sharded
    identically on both sides is allowed and makes the output PARTIAL on
    that axis (the Megatron row-parallel pattern); a K sharded on one
    side only forces that operand's K to replicate.
    """
    if x.ndim < 2 or y.ndim < 2:
        raise ValueError("matmul_rule expects ndim >= 2 operands")
    xm, xk = (-1, -2) if trans_x else (-2, -1)
    yk, yn = (-1, -2) if trans_y else (-2, -1)
    xdims = list(x.dims)
    ydims = list(y.dims)
    kx, ky = xdims[xk], ydims[yk]
    partial = set()
    if kx == ky and kx is not None:
        partial.update(x.axes_of(x.ndim + xk))
    else:
        # one-sided (or conflicting) contraction sharding → replicate K
        kx = ky = None
    xdims[xk], ydims[yk] = kx, ky
    nb = max(x.ndim, y.ndim) - 2
    out_batch = []
    xin = list(xdims)
    yin = list(ydims)
    for i in range(nb):
        xi = i - (nb - (x.ndim - 2))
        yi = i - (nb - (y.ndim - 2))
        a = xdims[xi] if xi >= 0 else None
        b = ydims[yi] if yi >= 0 else None
        m, conflict = _merge_dim(a, b)
        if conflict:
            m = None
            if xi >= 0:
                xin[xi] = None
            if yi >= 0:
                yin[yi] = None
        out_batch.append(m)
    m_axis = xin[xm]
    n_axis = yin[yn]
    # an axis cannot shard two output dims at once: priority
    # batch > N > M (a batch axis usually carries dp; N wins ties
    # with M — the Megatron column layout).  Compare FLATTENED axis
    # members so multi-axis dims (tuples) collide correctly.
    def _members(a):
        if a is None:
            return ()
        return a if isinstance(a, tuple) else (a,)

    used = set()
    for bdim in out_batch:
        used.update(_members(bdim))
    if n_axis is not None and used & set(_members(n_axis)):
        n_axis = None
        yin[yn] = None
    used.update(_members(n_axis))
    if m_axis is not None and used & set(_members(m_axis)):
        m_axis = None
        xin[xm] = None
    out = out_batch + [m_axis, n_axis]
    # matmul is linear in each operand, so ONE side's incoming partial
    # may flow through to the output; both sides partial would multiply
    # two pending sums — settle y first (reshard flagged via in_specs)
    y_partial = y.partial
    if x.partial and y.partial:
        y_partial = frozenset()
    return RuleResult([DistSpec(xin, x.partial),
                       DistSpec(yin, y_partial)],
                      [DistSpec(out,
                                partial | set(x.partial) | set(y_partial))])


def elementwise_rule(*specs: DistSpec,
                     shapes: Optional[Sequence[Sequence[int]]] = None
                     ) -> RuleResult:
    """Broadcast-aware elementwise merge (add/mul/...).

    Right-aligned dims merge; a conflict replicates the dim.  Inputs
    carrying partial sums keep them only if EVERY input is partial on
    the same axes (else the add of a partial with a replicated operand
    would double-count — callers must all-reduce first, which the
    returned in_specs express by dropping ``partial``).
    """
    nd = max(s.ndim for s in specs)
    common_partial = frozenset.intersection(*[s.partial for s in specs]) \
        if specs else frozenset()
    out_dims: List = []
    new_in = [list(s.dims) for s in specs]
    for d in range(nd):
        cands = []
        for si, s in enumerate(specs):
            i = d - (nd - s.ndim)
            if i >= 0:
                size = shapes[si][i] if shapes else None
                if size == 1:
                    continue      # broadcasting dim: sharding irrelevant
                cands.append((si, i, s.dims[i]))
        merged = None
        for _, _, a in cands:
            m, conflict = _merge_dim(merged, a)
            merged = None if conflict else m
            if conflict:
                break
        out_dims.append(merged)
        for si, i, a in cands:
            if a != merged and a is not None:
                new_in[si][i] = merged
    ins = [DistSpec(dims, s.partial & common_partial)
           for dims, s in zip(new_in, specs)]
    return RuleResult(ins, [DistSpec(out_dims, common_partial)])


def multiply_rule(*specs: DistSpec,
                  shapes: Optional[Sequence[Sequence[int]]] = None
                  ) -> RuleResult:
    """Elementwise multiply/divide: partial sums do NOT distribute
    through a product (Σaᵢ·Σbᵢ ≠ Σaᵢbᵢ), so every input must settle
    its partials first; dims merge as in elementwise_rule."""
    r = elementwise_rule(*[s.drop_partial() for s in specs],
                         shapes=shapes)
    return RuleResult(r.in_specs, [r.out_spec.drop_partial()])


def reduction_rule(x: DistSpec, axes: Sequence[int],
                   keepdim: bool = False) -> RuleResult:
    """SUM over ``axes``: reduced dims' mesh axes become partial on the
    output (Σ distributes over shards); kept dims propagate."""
    axes = [a % x.ndim for a in axes]
    partial = set(x.partial)
    out_dims: List = []
    for i, d in enumerate(x.dims):
        if i in axes:
            partial.update(x.axes_of(i))
            if keepdim:
                out_dims.append(None)
        else:
            out_dims.append(d)
    return RuleResult([x], [DistSpec(out_dims, partial)])


def nonlinear_reduction_rule(x: DistSpec, axes: Sequence[int],
                             keepdim: bool = False) -> RuleResult:
    """mean/max/min over ``axes``: shard-wise results do not combine by
    summation (Σ of shard means ≠ global mean; Σ of shard maxes is
    meaningless), so the reduced dims must be REPLICATED first —
    expressed as an input reshard, never as a Partial output."""
    axes = [a % x.ndim for a in axes]
    in_dims = [None if i in axes else d for i, d in enumerate(x.dims)]
    out_dims = [d for i, d in enumerate(in_dims)
                if i not in axes or keepdim]
    xin = DistSpec(in_dims)
    return RuleResult([xin], [DistSpec(out_dims)])


def reshape_rule(x: DistSpec, in_shape: Sequence[int],
                 out_shape: Sequence[int]) -> RuleResult:
    """Propagate sharding through reshape when a sharded input dim maps
    to an output dim it left-aligns with (leading-factor rule: the
    sharded dim must be the MAJOR factor of its group).  Anything more
    exotic replicates."""
    groups = _reshape_groups(list(in_shape), list(out_shape))
    if groups is None:
        return RuleResult([x.drop_partial()],
                          [replicated(len(out_shape))])
    out_dims: List = [None] * len(out_shape)
    new_in = list(x.dims)
    for in_dims, out_dims_idx in groups:
        shard = [i for i in in_dims if x.dims[i] is not None]
        if not shard:
            continue
        lead = in_dims[0]
        if shard != [lead]:
            for i in shard:           # non-leading shard: replicate
                new_in[i] = None
            continue
        out_dims[out_dims_idx[0]] = x.dims[lead]
    return RuleResult([DistSpec(new_in, x.partial)],
                      [DistSpec(out_dims, x.partial)])


def _reshape_groups(a: List[int], b: List[int]):
    """Greedy factor grouping: returns [(in_dim_idxs, out_dim_idxs)]
    covering both shapes, or None when sizes cannot be grouped."""
    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        ii, jj = [i], [j]
        pa, pb = a[i], b[j]
        i += 1
        j += 1
        while pa != pb:
            if pa < pb:
                if i >= len(a):
                    return None
                pa *= a[i]
                ii.append(i)
                i += 1
            else:
                if j >= len(b):
                    return None
                pb *= b[j]
                jj.append(j)
                j += 1
        out.append((ii, jj))
    if i < len(a) or j < len(b):      # trailing 1s
        if all(v == 1 for v in a[i:]) and all(v == 1 for v in b[j:]):
            return out
        return None
    return out


def transpose_rule(x: DistSpec, perm: Sequence[int]) -> RuleResult:
    return RuleResult([x], [DistSpec([x.dims[p] for p in perm],
                                     x.partial)])


def embedding_rule(table: DistSpec, ids: DistSpec) -> RuleResult:
    """Gather rows: vocab-sharded table ([mp, None]) makes the output
    PARTIAL on the vocab axis (out-of-shard rows contribute zero — the
    VocabParallelEmbedding masked-lookup pattern); hidden-dim sharding
    propagates to the last output dim."""
    vocab_axes = table.axes_of(0)
    out_dims = list(ids.dims) + [table.dims[1] if table.ndim > 1
                                 else None]
    return RuleResult([table, ids],
                      [DistSpec(out_dims,
                                set(table.partial) | set(vocab_axes))])


def softmax_rule(x: DistSpec, axis: int = -1) -> RuleResult:
    """The normalized axis must not be sharded (a sharded softmax dim
    needs the mp all-reduce pattern instead) → rule requires that dim
    replicated; other dims propagate."""
    axis = axis % x.ndim
    xin = x
    if x.dims[axis] is not None:
        xin = x.with_dim(axis, None)
    return RuleResult([xin.drop_partial()], [xin.drop_partial()])


def layer_norm_rule(x: DistSpec, begin_norm_axis: int = -1) -> RuleResult:
    """Normalized (trailing) dims replicate; leading dims propagate."""
    begin = begin_norm_axis % x.ndim
    dims = [d if i < begin else None for i, d in enumerate(x.dims)]
    return RuleResult([DistSpec(dims)], [DistSpec(dims)])


def concat_rule(specs: Sequence[DistSpec], axis: int) -> RuleResult:
    """Concat dim must be replicated on every input; others merge."""
    nd = specs[0].ndim
    axis = axis % nd
    merged: List = []
    for d in range(nd):
        m = None
        for s in specs:
            m, conflict = _merge_dim(m, s.dims[d])
            if conflict:
                m = None
                break
        merged.append(None if d == axis else m)
    ins = [DistSpec(merged) for _ in specs]
    return RuleResult(list(ins), [DistSpec(merged)])


def split_rule(x: DistSpec, axis: int, num: int) -> RuleResult:
    axis = axis % x.ndim
    xin = x.with_dim(axis, None) if x.dims[axis] is not None else x
    return RuleResult([xin], [xin] * num)


def flash_attention_rule(q: DistSpec, k: DistSpec, v: DistSpec
                         ) -> RuleResult:
    """[B, S, H, D]: batch merges across q/k/v; heads may shard (mp);
    D replicates; S may shard only on a context-parallel axis for q
    (ring/Ulysses handle the K/V exchange) — the plain kernel requires
    K/V sequence replicated."""
    b, _ = _merge_dim(_merge_dim(q.dims[0], k.dims[0])[0], v.dims[0])
    h, _ = _merge_dim(_merge_dim(q.dims[2], k.dims[2])[0], v.dims[2])
    qs = DistSpec([b, q.dims[1], h, None])
    kv = DistSpec([b, None, h, None])
    return RuleResult([qs, kv, kv], [qs])


def cross_entropy_rule(logits: DistSpec, label: DistSpec) -> RuleResult:
    """Vocab (last) dim sharded → ParallelCrossEntropy: output loss is
    partial on the vocab axes; batch dims merge with the label.  CE is
    nonlinear in the logits, so an INCOMING partial must settle first
    (reshard flagged by dropping it from in_specs)."""
    vocab_axes = logits.axes_of(logits.ndim - 1)
    out_dims = []
    lin = list(label.dims)
    for i in range(logits.ndim - 1):
        m, conflict = _merge_dim(logits.dims[i],
                                 label.dims[i] if i < label.ndim else None)
        if conflict:
            m = None
        out_dims.append(m)
        if i < label.ndim:
            lin[i] = m
    return RuleResult([logits.drop_partial(),
                       DistSpec(lin, label.partial)],
                      [DistSpec(out_dims, set(vocab_axes))])


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------
def conv2d_rule(x: DistSpec, w: DistSpec,
                data_format: str = "NCHW") -> RuleResult:
    """x [N, Cin, H, W], w [Cout, Cin, kh, kw] (NCHW).

    Shardable dims: batch (data parallel) and the channel pair —
    w sharded on Cout → output channel-sharded; x-Cin and w-Cin sharded
    on the SAME axis → output partial (the conv contracts over Cin,
    exactly matmul's k-dim rule).  Spatial dims must be replicated
    (halo exchange is not modeled — upstream reshards them too)."""
    n_ax = 0
    c_ax = 1 if data_format == "NCHW" else 3
    batch = x.axes_of(n_ax) or None
    cin_x, cin_w = x.axes_of(c_ax), w.axes_of(1)
    cout = w.axes_of(0) or None
    contracted = tuple(a for a in cin_x if a in cin_w)

    def _members(a):
        if not a:
            return ()
        return a if isinstance(a, tuple) else (a,)

    # one mesh axis cannot shard two output dims: batch wins over Cout
    # (same priority scheme as matmul_rule)
    if cout is not None and set(_members(cout)) & set(_members(batch)):
        cout = None
    x_in = DistSpec([batch if i == n_ax else
                     (contracted or None if i == c_ax else None)
                     for i in range(4)])
    w_in = DistSpec([cout, contracted or None, None, None])
    out_dims = [None] * 4
    out_dims[n_ax] = batch
    out_dims[c_ax] = cout
    out = DistSpec(out_dims, partial=contracted)
    return RuleResult([x_in, w_in], [out])


def pool2d_rule(x: DistSpec,
                data_format: str = "NCHW") -> RuleResult:
    """Pooling / spatial resampling: batch + channel pass through,
    spatial dims replicated."""
    keep = (0, 1) if data_format == "NCHW" else (0, x.ndim - 1)
    x_in = DistSpec([(x.axes_of(i) or None) if i in keep else None
                     for i in range(x.ndim)])
    return RuleResult([x_in], [x_in])


def batch_norm_rule(x: DistSpec,
                    data_format: str = "NCHW") -> RuleResult:
    """BatchNorm: batch + channel shardings pass through the
    ACTIVATION unchanged.  The 2*C batch statistics are what become
    partial over the batch axes — a tiny psum the op performs
    internally (sync-BN), deliberately NOT marked on the activation
    spec: the activation itself is never a pending sum, and pricing a
    full-tensor settle here would overcharge every dp conv plan."""
    c_ax = 1 if data_format == "NCHW" else x.ndim - 1
    x_in = DistSpec([(x.axes_of(i) or None) if i in (0, c_ax) else None
                     for i in range(x.ndim)])
    return RuleResult([x_in], [x_in])


def unary_rule(x: DistSpec, **_attrs) -> RuleResult:
    """Shape-preserving unary op (relu/gelu/exp/cast/scale/dropout...):
    any placement passes through (upstream default_data_parallel /
    elementwise unary rules)."""
    return RuleResult([x], [x])


def slice_rule(x: DistSpec, axes: Sequence[int], **_attrs) -> RuleResult:
    """Slicing along ``axes``: those dims must be replicated (a shard
    boundary can't cut a slice window deterministically); others pass
    through (upstream slice spmd rule)."""
    dims = list(x.dims)
    for a in axes:
        dims[a % len(dims)] = None
    s = DistSpec(tuple(dims))
    return RuleResult([s], [s])


def gather_rule(x: DistSpec, index: DistSpec, axis: int = 0) -> RuleResult:
    """Gather rows along ``axis``: the gathered dim replicates (like
    embedding); index keeps its placement; output = index dims +
    x's trailing dims."""
    dims = list(x.dims)
    axis %= len(dims)
    dims[axis] = None
    out = tuple(index.dims) + tuple(dims[axis + 1:])
    return RuleResult([DistSpec(tuple(dims)), index], [DistSpec(out)])


def stack_rule(specs: Sequence[DistSpec], axis: int = 0) -> RuleResult:
    """Stack: operands merge dim-wise (conflict → replicate), new axis
    is replicated."""
    nd = len(specs[0].dims)
    merged = []
    for d in range(nd):
        cur = specs[0].dims[d]
        for s in specs[1:]:
            cur, _ = _merge_dim(cur, s.dims[d])
        merged.append(cur)
    ins = [DistSpec(tuple(merged))] * len(specs)
    out = list(merged)
    out.insert(axis % (nd + 1), None)
    return RuleResult(list(ins), [DistSpec(tuple(out))])


def squeeze_rule(x: DistSpec, axes: Sequence[int]) -> RuleResult:
    nd = len(x.dims)
    drop = {a % nd for a in axes}
    out = tuple(d for i, d in enumerate(x.dims) if i not in drop)
    ins = tuple(None if i in drop else d for i, d in enumerate(x.dims))
    return RuleResult([DistSpec(ins)], [DistSpec(out)])


def unsqueeze_rule(x: DistSpec, axes: Sequence[int]) -> RuleResult:
    out = list(x.dims)
    for a in sorted(a % (len(x.dims) + 1) for a in axes):
        out.insert(a, None)
    return RuleResult([x], [DistSpec(tuple(out))])


def tile_rule(x: DistSpec, repeats: Sequence[int]) -> RuleResult:
    """Tiled dims must be replicated (shards would interleave wrong);
    repeat==1 dims pass through."""
    dims = list(x.dims)
    off = len(dims) - len(repeats)
    for i, r in enumerate(repeats):
        if r != 1 and 0 <= off + i < len(dims):
            dims[off + i] = None
    s = DistSpec(tuple(dims))
    return RuleResult([s], [s])


def cumsum_rule(x: DistSpec, axis: int = 0) -> RuleResult:
    """Scan along a dim: that dim must be replicated (cross-shard
    carry), others pass through."""
    dims = list(x.dims)
    dims[axis % len(dims)] = None
    s = DistSpec(tuple(dims))
    return RuleResult([s], [s])


def arg_reduce_rule(x: DistSpec, axis: int = -1,
                    keepdim: bool = False) -> RuleResult:
    """argmax/argmin along ``axis``: the reduced dim must be
    replicated (index semantics don't compose across shards via psum);
    output drops (or keeps) it."""
    nd = len(x.dims)
    axis %= nd
    dims = list(x.dims)
    dims[axis] = None
    out = list(dims)
    if keepdim:
        out[axis] = None
    else:
        out.pop(axis)
    return RuleResult([DistSpec(tuple(dims))], [DistSpec(tuple(out))])


def topk_rule(x: DistSpec, axis: int = -1) -> RuleResult:
    """top-k along ``axis``: dim replicated; two outputs (values,
    indices) share the input placement."""
    nd = len(x.dims)
    dims = list(x.dims)
    dims[axis % nd] = None
    s = DistSpec(tuple(dims))
    return RuleResult([s], [s, s])


def one_hot_rule(x: DistSpec, **_attrs) -> RuleResult:
    """Output appends a replicated class dim."""
    return RuleResult([x], [DistSpec(tuple(x.dims) + (None,))])


def where_rule(cond: DistSpec, x: DistSpec, y: DistSpec) -> RuleResult:
    return elementwise_rule(cond, x, y)


def scatter_rule(x: DistSpec, index: DistSpec,
                 updates: DistSpec, axis: int = 0) -> RuleResult:
    """Scatter along ``axis``: destination dim replicated (shards
    can't own foreign rows); index/updates replicated on that dim."""
    dims = list(x.dims)
    axis %= len(dims)
    dims[axis] = None
    xs = DistSpec(tuple(dims))
    idx = DistSpec((None,) * len(index.dims))
    ups = DistSpec((None,) + tuple(dims[1:])
                   if len(updates.dims) == len(dims)
                   else (None,) * len(updates.dims))
    return RuleResult([xs, idx, ups], [xs])


def flatten_rule(x: DistSpec, start_axis: int = 0,
                 stop_axis: int = -1) -> RuleResult:
    """Flatten [start, stop] into one dim: the merged output dim keeps
    the FIRST merged input dim's sharding (the major dim owns the
    stride); later merged dims must be replicated (upstream
    flatten/reshape rule behavior)."""
    nd = x.ndim
    start = start_axis % nd
    stop = stop_axis % nd
    in_dims = list(x.dims)
    for i in range(start + 1, stop + 1):
        in_dims[i] = None
    out_dims = (in_dims[:start] + [in_dims[start]]
                + in_dims[stop + 1:])
    return RuleResult([DistSpec(in_dims)], [DistSpec(out_dims)])


def pad_rule(x: DistSpec, paddings: Sequence[int] = (),
             **_attrs) -> RuleResult:
    """Padded dims must be replicated (a shard can't know whether it
    owns the global edge); unpadded dims propagate.  ``paddings`` is
    the flat (before, after) pairs list; a SHORT list applies to the
    TRAILING dims (paddle.pad's convention — ops/manipulation.py);
    missing/empty means all dims padded (conservative)."""
    dims = list(x.dims)
    if paddings:
        pairs = list(zip(paddings[0::2], paddings[1::2]))
        # align to trailing dims: pad=[1,1] on NCHW pads W only
        offset = len(dims) - len(pairs)
        for j, (lo, hi) in enumerate(pairs):
            i = offset + j
            if 0 <= i < len(dims) and (lo or hi):
                dims[i] = None
    else:
        dims = [None] * len(dims)
    s = DistSpec(dims)
    return RuleResult([s], [s])


def tri_rule(x: DistSpec, **_attrs) -> RuleResult:
    """triu/tril: the mask is a pure function of GLOBAL indices, which
    SPMD iota provides per shard — every placement passes through."""
    return RuleResult([x], [x])


def roll_rule(x: DistSpec, axis=None, **_attrs) -> RuleResult:
    """Rolled dims need neighbor data across shard boundaries —
    replicate them; ``axis=None`` (flattened roll) replicates all."""
    dims = list(x.dims)
    if axis is None:
        dims = [None] * len(dims)
    else:
        axes = axis if isinstance(axis, (list, tuple)) else (axis,)
        for a in axes:
            dims[a % len(dims)] = None
    s = DistSpec(dims)
    return RuleResult([s], [s])


def rms_norm_rule(x: DistSpec, begin_norm_axis: int = -1) -> RuleResult:
    """Same constraint shape as layer_norm (upstream rms_norm spmd
    rule): normalized trailing dims replicate, leading propagate."""
    return layer_norm_rule(x, begin_norm_axis)


def group_norm_rule(x: DistSpec, **_attrs) -> RuleResult:
    """NCHW group norm: stats span C-within-group and all spatial dims —
    batch may shard, channel/spatial replicate (upstream group_norm
    rule's conservative form)."""
    dims = [x.dims[0]] + [None] * (x.ndim - 1)
    s = DistSpec(dims)
    return RuleResult([s], [s])


def instance_norm_rule(x: DistSpec, **_attrs) -> RuleResult:
    """NCHW instance norm: stats per (N, C) over spatial dims — N and C
    may shard, spatial dims replicate."""
    dims = [x.dims[0], x.dims[1] if x.ndim > 1 else None] \
        + [None] * max(x.ndim - 2, 0)
    s = DistSpec(dims)
    return RuleResult([s], [s])


def fused_rope_rule(*qkv: DistSpec, **_attrs) -> RuleResult:
    """Rotary embedding over (q[, k[, v]]) each [b, s, h, d]: rotation
    pairs live inside the head-feature dim — batch/seq/heads propagate
    (merged across the given operands), the feature dim replicates
    (upstream fused_rotary_position_embedding rule).  One out spec per
    input."""
    nd = qkv[0].ndim
    merged: List = []
    for d in range(nd):
        m = None
        for s in qkv:
            m, conflict = _merge_dim(m, s.dims[d])
            if conflict:
                m = None
                break
        merged.append(m)
    merged[-1] = None
    spec = DistSpec(merged)
    return RuleResult([spec] * len(qkv), [spec] * len(qkv))


def swiglu_rule(x: DistSpec, y: Optional[DistSpec] = None,
                **_attrs) -> RuleResult:
    """swiglu: one-tensor form splits the last dim into (gate, value)
    halves — a last-dim shard would mix halves, so it replicates;
    two-tensor form silu(x)*y is elementwise and the last dim merges
    like any elementwise op.  Leading dims propagate (merged)."""
    if y is None:
        dims = list(x.dims)
        dims[-1] = None
        s = DistSpec(dims)
        return RuleResult([s], [s])
    merged: List = []
    for d in range(x.ndim):
        m, conflict = _merge_dim(x.dims[d], y.dims[d])
        merged.append(None if conflict else m)
    s = DistSpec(merged)
    return RuleResult([s, s], [s])


def vector_norm_rule(x: DistSpec, axis=None, keepdim: bool = False,
                     **_attrs) -> RuleResult:
    """p_norm / squared_l2_norm: nonlinear reduction — the final
    root/power is not sum-decomposable, so reduced dims must replicate
    first.  ``axis=None`` (full reduction to a scalar) replicates
    everything; an axis list keeps the surviving dims sharded."""
    if axis is None:
        return RuleResult([replicated(x.ndim)], [DistSpec(())])
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    return nonlinear_reduction_rule(x, list(axes), keepdim=keepdim)


def take_along_axis_rule(x: DistSpec, index: DistSpec,
                         axis: int = 0) -> RuleResult:
    """take_along_axis output has INDEX's rank/shape (unlike gather):
    the indexed dim replicates on both operands, the other dims merge
    between x and index and pass through to the output."""
    nd = x.ndim
    axis %= nd
    xd, idxd, outd = [], [], []
    for d in range(nd):
        if d == axis:
            xd.append(None)
            idxd.append(None)
            outd.append(None)
            continue
        m, conflict = _merge_dim(x.dims[d],
                                 index.dims[d] if d < index.ndim
                                 else None)
        m = None if conflict else m
        xd.append(m)
        idxd.append(m)
        outd.append(m)
    return RuleResult([DistSpec(xd), DistSpec(idxd[:index.ndim])],
                      [DistSpec(outd[:index.ndim])])


def unbind_rule(x: DistSpec, axis: int = 0) -> RuleResult:
    """Unbind removes ``axis``: that dim replicates, the rest pass
    through to every output."""
    nd = x.ndim
    axis = axis % nd
    dims = list(x.dims)
    dims[axis] = None
    out = DistSpec(dims[:axis] + dims[axis + 1:])
    return RuleResult([DistSpec(dims)], [out])


_RULES = {
    "matmul": matmul_rule,
    "conv2d": conv2d_rule,
    "pool2d": pool2d_rule,
    "interpolate": pool2d_rule,
    "batch_norm": batch_norm_rule,
    "elementwise": elementwise_rule,
    "add": elementwise_rule,
    "multiply": multiply_rule,
    "divide": multiply_rule,
    "reduction": reduction_rule,
    "sum": reduction_rule,
    "mean": nonlinear_reduction_rule,
    "max": nonlinear_reduction_rule,
    "min": nonlinear_reduction_rule,
    "reshape": reshape_rule,
    "transpose": transpose_rule,
    "embedding": embedding_rule,
    "softmax": softmax_rule,
    "layer_norm": layer_norm_rule,
    "concat": concat_rule,
    "split": split_rule,
    "flash_attention": flash_attention_rule,
    "cross_entropy": cross_entropy_rule,
    # round-5 per-op widening (VERDICT r4 #4: upstream has per-op
    # rules; these cover the remaining common op classes)
    "unary": unary_rule,
    "relu": unary_rule,
    "gelu": unary_rule,
    "cast": unary_rule,
    "scale": unary_rule,
    "dropout": unary_rule,
    "slice": slice_rule,
    "gather": gather_rule,
    "index_select": gather_rule,
    "stack": stack_rule,
    "squeeze": squeeze_rule,
    "unsqueeze": unsqueeze_rule,
    "tile": tile_rule,
    "expand": tile_rule,
    "cumsum": cumsum_rule,
    "argmax": arg_reduce_rule,
    "argmin": arg_reduce_rule,
    "topk": topk_rule,
    "one_hot": one_hot_rule,
    "where": where_rule,
    "scatter": scatter_rule,
    "put_along_axis": scatter_rule,
    # second round-5 widening batch (upstream per-op rule parity)
    "flatten": flatten_rule,
    "pad": pad_rule,
    "triu": tri_rule,
    "tril": tri_rule,
    "roll": roll_rule,
    "rms_norm": rms_norm_rule,
    "group_norm": group_norm_rule,
    "instance_norm": instance_norm_rule,
    "fused_rotary_position_embedding": fused_rope_rule,
    "fused_rope": fused_rope_rule,
    "swiglu": swiglu_rule,
    "p_norm": vector_norm_rule,
    "squared_l2_norm": vector_norm_rule,
    "unbind": unbind_rule,
    "take_along_axis": take_along_axis_rule,
    "bmm": matmul_rule,
    "clip": unary_rule,
    "amax": nonlinear_reduction_rule,
    "amin": nonlinear_reduction_rule,
    "logsumexp": nonlinear_reduction_rule,
}


def infer_forward(op: str, *specs, **attrs) -> RuleResult:
    """Look up and apply the SPMD rule for ``op`` (upstream
    ``SpmdRuleFactory`` entry point)."""
    try:
        rule = _RULES[op]
    except KeyError:
        raise NotImplementedError(
            f"no SPMD rule registered for op {op!r}; known: "
            f"{sorted(_RULES)}") from None
    return rule(*specs, **attrs)
