"""Global mesh registry + tensor-parallel split helper (the analog of
upstream's communicator bookkeeping in paddle.distributed.collective).

The Mesh is THE central object of the TPU build (SURVEY.md §5.8): axes
('dp','sharding','pp','sep','mp') ordered DCN-outer → ICI-inner so
model-parallel collectives ride ICI.  Built by fleet.init from
DistributedStrategy.hybrid_configs; consumed by every jit'ed step.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_GLOBAL_MESH: Optional[Mesh] = None

AXIS_ORDER = ("pp", "dp", "sharding", "sep", "mp")
# pp outermost: pipeline stages tolerate DCN latency; mp innermost:
# per-layer allreduce needs ICI bandwidth (scaling-book recipe).


def build_mesh(degrees: Dict[str, int],
               devices: Optional[Sequence] = None) -> Mesh:
    """degrees: axis name → size. Missing axes get size 1 (kept in the
    mesh so shardings can always name them)."""
    if devices is None:
        devices = jax.devices()
    sizes = [int(degrees.get(a, 1)) for a in AXIS_ORDER]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh needs {total} devices, have {len(devices)}")
    devices = list(devices)[:total]
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, AXIS_ORDER)


def set_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH


def ensure_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = build_mesh({})
    return _GLOBAL_MESH


def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(ensure_mesh(), PartitionSpec(*spec))


def data_axes(mesh: Mesh):
    """Mesh axes that carry the batch dimension (>1 only)."""
    return tuple(a for a in ("dp", "sharding")
                 if a in mesh.axis_names and mesh.shape[a] > 1)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split — megatron-style parallel embedding/fc
    helper.  Provided for API parity; prefer fleet.meta_parallel layers."""
    from .fleet.meta_parallel import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)
    if operation == "linear":
        if axis == 0:
            return RowParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     has_bias=bias_attr is not False)(x)
        return ColumnParallelLinear(size[0], size[1],
                                    weight_attr=weight_attr,
                                    gather_output=gather_out,
                                    has_bias=bias_attr is not False)(x)
    if operation == "embedding":
        return VocabParallelEmbedding(size[0], size[1],
                                      weight_attr=weight_attr)(x)
    raise ValueError(f"unknown operation {operation}")
