"""paddle.distributed parity surface (python/paddle/distributed/).

Architecture (SURVEY.md §5.8): there is no runtime comm library —
collectives are XLA HLO ops compiled onto ICI/DCN.  This package is
(a) the mesh/axis manager (fleet.topology → jax.sharding.Mesh),
(b) functional collectives (shard_map-wrapped psum/all_gather/... for
dygraph parity, free fusion under jit),
(c) the host control plane (jax.distributed ≈ TCPStore rendezvous),
(d) the launch CLI with the PADDLE_TRAINER_* env contract.
"""

from .parallel import (  # noqa
    ParallelEnv, init_parallel_env, get_rank, get_world_size,
    is_initialized, DataParallel)
from .communication import (  # noqa
    all_reduce, all_gather, broadcast, reduce, reduce_scatter, alltoall,
    all_to_all, send, recv, isend, irecv, scatter, barrier, new_group,
    wait, ReduceOp, get_group, all_gather_object, alltoall_single,
    broadcast_object_list, scatter_object_list, gather,
    destroy_process_group)
from . import stream  # noqa
from . import fleet  # noqa
from . import sharding  # noqa
from .collective import split, get_mesh, set_mesh  # noqa
from .runner import DistributedRunner  # noqa
from .spawn import spawn  # noqa
from .compressed import (  # noqa
    quantized_all_reduce, bf16_all_reduce, compressed_psum_tree)
from .fleet.recompute import recompute  # noqa
from . import checkpoint  # noqa
from . import resilience  # noqa
from . import passes  # noqa

# auto-parallel style API
from .auto_parallel.api import (  # noqa
    ProcessMesh, shard_tensor, shard_op, dtensor_from_fn, reshard,
    shard_dataloader, Placement, Replicate, Shard, Partial)
from .auto_parallel.engine import Engine, DistModel, to_static  # noqa


def launch():
    from .launch.main import main
    main()
