"""Compressed cross-slice collectives (DCN story — SURVEY.md §5.8 /
build-plan M6; technique: EQuARX, arxiv 2506.17615).

Multi-slice TPU jobs reduce gradients over two link classes: ICI inside
a slice (fast) and DCN between slices (~10-40x slower).  The DCN hop
dominates scaling efficiency at 256+ chips, and gradients tolerate
lossy compression — so the outer (dp/DCN) all-reduce can run quantized
while the inner (ICI) collectives stay exact.

This module implements the EQuARX recipe as portable XLA (shard_map +
ppermute), testable on the virtual CPU mesh:

- ``quantized_all_reduce(x, axis_name, bits=8, block=256)``: ring
  reduce-scatter + ring all-gather where every hop's payload is
  block-quantized int8 with a per-block fp16-class scale.  Wire volume
  ≈ (8 + 16/block) bits per element per hop vs 32 — a ~3.6x DCN
  bandwidth cut.  Accumulation happens in fp32 AFTER dequantization at
  each hop (the EQuARX "dequant-accumulate-requant" pipeline), so the
  error is O(W) quantization noise, not compounding bias: stochastic
  rounding keeps it zero-mean.
- ``bits=16`` runs the SAME ring with a bit-exact payload: each fp32
  element crosses as two 16-bit wire words (its raw high/low halves)
  and is reassembled exactly.  No bandwidth win (32 bits on the wire)
  — this mode exists as the *parity anchor* of the explicit-collective
  machinery: at dp=2 the single-hop sum is order-invariant, so a
  training run through the explicit ring is bit-identical to the
  implicit XLA all-reduce, isolating bits=8's deviation to the
  quantizer alone (pinned by ``tests/test_dp_compressed.py``).
- ``ring_reduce_scatter(x, axis_name, shard_axis, bits)``: the
  reduce-scatter half on its own — the gradient side of the
  cross-replica sharded weight update (PAPERS.md arxiv 2004.13336):
  rank r keeps only shard r of the summed tensor, quantizable with the
  same wire modes.
- ``bf16_all_reduce``: the cheap 2x variant (upstream DistributedStrategy
  ``fp16_allreduce`` analog; bf16 on TPU).

All are pure jax functions usable inside any shard_map over the target
mesh axis; `hybrid dp = (dcn_dp, ici_dp)` meshes apply them on the
outer axis only (see DESIGN-DCN.md for the placement rules and the
scaling-efficiency model).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _block_quant(x, block, bits, key):
    """x: [N] fp → (int8[N], scales[N/block]) with stochastic rounding.

    Stochastic rounding makes the quantization error zero-mean, so ring
    accumulation over W hops grows noise as sqrt(W), not W."""
    q_max = float(2 ** (bits - 1) - 1)
    xb = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(xb), axis=1, keepdims=True) / q_max
    # quantize with the SAME bf16-rounded scale the receiver will
    # dequantize with — otherwise the scale's rounding is a coherent
    # per-block multiplicative bias instead of zero-mean noise
    scale = jnp.maximum(scale, 1e-30).astype(jnp.bfloat16)
    y = xb / scale.astype(jnp.float32)
    noise = jax.random.uniform(key, y.shape, y.dtype, -0.5, 0.5)
    q = jnp.clip(jnp.round(y + noise), -q_max, q_max).astype(jnp.int8)
    return q, scale


def _block_dequant(q, scale):
    return (q.astype(jnp.float32) *
            scale.astype(jnp.float32)).reshape(-1)


def _split16(x):
    """Lossless fp32 → two 16-bit wire words (raw high/low halves of
    the bit pattern).  The high half IS the bf16 truncation of x; the
    low half carries the remaining mantissa bits, so ``_merge16``
    reassembles the exact fp32 value.  bits=16's payload codec."""
    u = lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    return ((u >> 16).astype(jnp.uint16),
            (u & jnp.uint32(0xFFFF)).astype(jnp.uint16))


def _merge16(hi, lo):
    u = (hi.astype(jnp.uint32) << 16) | lo.astype(jnp.uint32)
    return lax.bitcast_convert_type(u, jnp.float32)


def _encode_hop(x, bits, block, key):
    """One hop's wire payload for fp32 ``x``: a tuple of arrays that
    cross the link (everything else stays local).  bits=8: int8 blocks
    + bf16 scales (lossy, stochastic-rounded); bits=16: exact
    high/low 16-bit halves (lossless)."""
    if bits == 16:
        return _split16(x)
    q, sc = _block_quant(x, block, bits, key)
    return (q, sc)


def _decode_hop(payload, bits, shape):
    if bits == 16:
        return _merge16(*payload).reshape(shape)
    q, sc = payload
    return _block_dequant(q, sc).reshape(shape)


def _ppermute_payload(payload, axis_name, perm):
    return tuple(lax.ppermute(p, axis_name, perm) for p in payload)


def wire_bits_per_element(bits: int, block: int = 256) -> float:
    """Wire cost of one fp32 element on one hop under a mode: 8 →
    int8 + amortized bf16 block scale; 16 → the exact 2x16-bit split
    (no win — the parity anchor); 0/None → plain fp32."""
    if bits == 8:
        return 8.0 + 16.0 / block
    return 32.0


def dp_comm_bytes_per_step(n_elems: int, world: int, bits: int,
                           sharded_update: bool,
                           block: int = 256) -> int:
    """Modeled per-device dp-axis bytes for one train step (the
    quantity `dp_allreduce_bytes_total` counts and the bench's
    bytes-moved proxy cross-checks against compiled HLO):

    - unsharded: ring all-reduce of N grad elements = reduce-scatter +
      all-gather, both at the mode's wire width;
    - sharded update: reduce-scatter of grads at the mode's wire width
      + all-gather of the updated params at full fp32 (weights are
      state — persistent error is not zero-mean like grad noise, so
      the param gather is never quantized)."""
    if world <= 1:
        return 0
    hops = (world - 1) / world * n_elems
    grad_bits = wire_bits_per_element(bits or 0, block)
    if sharded_update:
        return int(hops * (grad_bits + 32.0) / 8)
    return int(2 * hops * grad_bits / 8)


def _scatter_row(arr, idx, row):
    return arr.at[idx].set(row)     # idx may be a traced axis_index


def _pad_to(x, mult):
    n = x.size
    pad = (-n) % mult
    flat = x.reshape(-1)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    return flat, n


def quantized_all_reduce(x, axis_name: str, bits: int = 8,
                         block: int = 256, key=None):
    """Sum-all-reduce over `axis_name` with a 16-or-8-bit-word wire
    format (bits=8: lossy int8 blocks; bits=16: exact — the parity
    anchor, see the module docstring).

    Must run inside shard_map/pmap binding `axis_name`.  The ring:
    W-1 reduce-scatter hops (each rank owns chunk r at the end) then
    W-1 all-gather hops; every payload crosses the link encoded.
    Returns fp32 of x's shape (cast back to x.dtype)."""
    from .shard_map_compat import axis_size
    W = axis_size(axis_name)
    if W == 1:
        return x
    r = lax.axis_index(axis_name)
    if key is None:
        key = jax.random.PRNGKey(0)
    key = jax.random.fold_in(key, r)

    orig_dtype = x.dtype
    flat, n = _pad_to(x.astype(jnp.float32), block * W)
    chunks = flat.reshape(W, -1)          # [W, C]
    perm = [(i, (i + 1) % W) for i in range(W)]
    cshape = chunks[0].shape

    # ring reduce-scatter: step s sends the partial for chunk
    # (r - s) mod W; after W-1 steps rank r holds the full sum of
    # chunk (r+1) mod W.  W is the (small, static) DCN slice count, so
    # the ring is unrolled — each hop is one ppermute the scheduler can
    # overlap with the quantize/dequant of the next.
    acc = jnp.zeros_like(chunks[0])
    for s in range(W - 1):
        idx = (r - s) % W
        part = jnp.take(chunks, idx, axis=0) + acc
        key, sub = jax.random.split(key)
        payload = _encode_hop(part, bits, block, sub)
        payload = _ppermute_payload(payload, axis_name, perm)
        acc = _decode_hop(payload, bits, cshape)
    own = (r + 1) % W
    final = jnp.take(chunks, own, axis=0) + acc   # my chunk's full sum

    # ring all-gather of the encoded final chunks.  The owner scatters
    # the DECODED copy of its own payload — not the exact sum — so
    # every rank reconstructs the identical (once-quantized) value:
    # keeping the owner's chunk exact would leave each rank's params
    # a slightly different array, a silent cross-replica divergence
    # that random-walks the "replicated" weights apart step by step
    # (masked by check_vma=False in the runner's shard_map).
    key, sub = jax.random.split(key)
    payload = _encode_hop(final, bits, block, sub)
    out = jnp.zeros((W,) + final.shape, jnp.float32)
    out = _scatter_row(out, own, _decode_hop(payload, bits, cshape))
    for s in range(W - 1):
        payload = _ppermute_payload(payload, axis_name, perm)
        src = (r - s) % W                 # owner of the arriving chunk
        out = _scatter_row(out, src, _decode_hop(payload, bits, cshape))
    return out.reshape(-1)[:n].reshape(x.shape).astype(orig_dtype)


def ring_reduce_scatter(x, axis_name: str, shard_axis: int = 0,
                        bits: int = 8, block: int = 256, key=None):
    """Ring reduce-scatter with the compressed wire format: sums ``x``
    over ``axis_name`` and returns rank r's shard r along
    ``shard_axis`` (the same shard ``lax.psum_scatter(...,
    tiled=True)`` would own, so the result drops straight onto a
    ``PartitionSpec`` that shards ``shard_axis`` on the same mesh
    axis).  The axis size W must divide ``x.shape[shard_axis]``.

    This is the gradient half of the cross-replica sharded weight
    update: every partial crosses the link encoded (int8 blocks at
    bits=8, exact 16-bit halves at bits=16), the accumulate happens in
    fp32 after each decode."""
    from .shard_map_compat import axis_size
    W = axis_size(axis_name)
    if W == 1:
        return x
    r = lax.axis_index(axis_name)
    if key is None:
        key = jax.random.PRNGKey(0)
    key = jax.random.fold_in(key, r)

    orig_dtype = x.dtype
    xf = jnp.moveaxis(x.astype(jnp.float32), shard_axis, 0)
    lead = xf.shape[0]
    assert lead % W == 0, (x.shape, shard_axis, W)
    rows = xf.reshape(W, lead // W, *xf.shape[1:])     # [W, shard...]
    shard_shape = rows.shape[1:]
    size = math.prod(shard_shape)
    per = -(-size // block) * block      # block-pad each chunk row
    chunks = jnp.zeros((W, per), jnp.float32)
    chunks = chunks.at[:, :size].set(rows.reshape(W, -1))
    perm = [(i, (i + 1) % W) for i in range(W)]
    cshape = chunks[0].shape

    # step s: send the running partial for chunk (r - s - 1) mod W;
    # after W-1 hops rank r holds the full sum of its OWN chunk r
    acc = jnp.zeros_like(chunks[0])
    for s in range(W - 1):
        idx = (r - s - 1) % W
        part = jnp.take(chunks, idx, axis=0) + acc
        key, sub = jax.random.split(key)
        payload = _encode_hop(part, bits, block, sub)
        payload = _ppermute_payload(payload, axis_name, perm)
        acc = _decode_hop(payload, bits, cshape)
    own_sum = jnp.take(chunks, r, axis=0) + acc
    shard = own_sum[:size].reshape(shard_shape)
    return jnp.moveaxis(shard, 0, shard_axis).astype(orig_dtype)


def bf16_all_reduce(x, axis_name: str):
    """2x-compressed all-reduce: the psum OPERAND is bf16 so bf16 is
    what crosses the wire (casting back before the psum would put fp32
    on the link and save nothing).  Accumulation is bf16 — the standard
    fp16_allreduce trade; use the int8 ring when fp32 accumulation
    matters."""
    return lax.psum(x.astype(jnp.bfloat16), axis_name).astype(x.dtype)


def compressed_psum_tree(tree, axis_name: str, mode="int8",
                         key=None, **kw):
    """Apply the compressed all-reduce across a pytree of gradients.
    mode: 'int8'/8 (EQuARX ring), 'exact16'/16 (bit-exact ring, the
    parity anchor), 'bf16', or 'none' (exact psum)."""
    if mode == "none":
        return jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), tree)
    if mode == "bf16":
        return jax.tree_util.tree_map(
            lambda g: bf16_all_reduce(g, axis_name), tree)
    if mode in ("int8", 8):
        bits = 8
    elif mode in ("exact16", "int16", 16):
        bits = 16
    else:
        raise ValueError(f"unknown compressed allreduce mode {mode!r}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if key is None:
        key = jax.random.PRNGKey(17)
    out = []
    for i, leaf in enumerate(leaves):
        out.append(quantized_all_reduce(
            leaf, axis_name, bits=bits,
            key=jax.random.fold_in(key, i), **kw))
    return jax.tree_util.tree_unflatten(treedef, out)
