"""Compressed cross-slice collectives (DCN story — SURVEY.md §5.8 /
build-plan M6; technique: EQuARX, arxiv 2506.17615).

Multi-slice TPU jobs reduce gradients over two link classes: ICI inside
a slice (fast) and DCN between slices (~10-40x slower).  The DCN hop
dominates scaling efficiency at 256+ chips, and gradients tolerate
lossy compression — so the outer (dp/DCN) all-reduce can run quantized
while the inner (ICI) collectives stay exact.

This module implements the EQuARX recipe as portable XLA (shard_map +
ppermute), testable on the virtual CPU mesh:

- ``quantized_all_reduce(x, axis_name, bits=8, block=256)``: ring
  reduce-scatter + ring all-gather where every hop's payload is
  block-quantized int8 with a per-block fp16-class scale.  Wire volume
  ≈ (8 + 16/block) bits per element per hop vs 32 — a ~3.6x DCN
  bandwidth cut.  Accumulation happens in fp32 AFTER dequantization at
  each hop (the EQuARX "dequant-accumulate-requant" pipeline), so the
  error is O(W) quantization noise, not compounding bias: stochastic
  rounding keeps it zero-mean.
- ``bf16_all_reduce``: the cheap 2x variant (upstream DistributedStrategy
  ``fp16_allreduce`` analog; bf16 on TPU).

Both are pure jax functions usable inside any shard_map over the target
mesh axis; `hybrid dp = (dcn_dp, ici_dp)` meshes apply them on the
outer axis only (see DESIGN-DCN.md for the placement rules and the
scaling-efficiency model).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _block_quant(x, block, bits, key):
    """x: [N] fp → (int8[N], scales[N/block]) with stochastic rounding.

    Stochastic rounding makes the quantization error zero-mean, so ring
    accumulation over W hops grows noise as sqrt(W), not W."""
    q_max = float(2 ** (bits - 1) - 1)
    xb = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(xb), axis=1, keepdims=True) / q_max
    # quantize with the SAME bf16-rounded scale the receiver will
    # dequantize with — otherwise the scale's rounding is a coherent
    # per-block multiplicative bias instead of zero-mean noise
    scale = jnp.maximum(scale, 1e-30).astype(jnp.bfloat16)
    y = xb / scale.astype(jnp.float32)
    noise = jax.random.uniform(key, y.shape, y.dtype, -0.5, 0.5)
    q = jnp.clip(jnp.round(y + noise), -q_max, q_max).astype(jnp.int8)
    return q, scale


def _block_dequant(q, scale):
    return (q.astype(jnp.float32) *
            scale.astype(jnp.float32)).reshape(-1)


def _scatter_row(arr, idx, row):
    return arr.at[idx].set(row)     # idx may be a traced axis_index


def _pad_to(x, mult):
    n = x.size
    pad = (-n) % mult
    flat = x.reshape(-1)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    return flat, n


def quantized_all_reduce(x, axis_name: str, bits: int = 8,
                         block: int = 256, key=None):
    """Sum-all-reduce over `axis_name` with int`bits` wire format.

    Must run inside shard_map/pmap binding `axis_name`.  The ring:
    W-1 reduce-scatter hops (each rank owns chunk r at the end) then
    W-1 all-gather hops; every payload crosses the link quantized.
    Returns fp32 of x's shape (cast back to x.dtype)."""
    from .shard_map_compat import axis_size
    W = axis_size(axis_name)
    if W == 1:
        return x
    r = lax.axis_index(axis_name)
    if key is None:
        key = jax.random.PRNGKey(0)
    key = jax.random.fold_in(key, r)

    orig_dtype = x.dtype
    flat, n = _pad_to(x.astype(jnp.float32), block * W)
    chunks = flat.reshape(W, -1)          # [W, C]
    perm = [(i, (i + 1) % W) for i in range(W)]

    # ring reduce-scatter: step s sends the partial for chunk
    # (r - s) mod W; after W-1 steps rank r holds the full sum of
    # chunk (r+1) mod W.  W is the (small, static) DCN slice count, so
    # the ring is unrolled — each hop is one ppermute the scheduler can
    # overlap with the quantize/dequant of the next.
    acc = jnp.zeros_like(chunks[0])
    for s in range(W - 1):
        idx = (r - s) % W
        part = jnp.take(chunks, idx, axis=0) + acc
        key, sub = jax.random.split(key)
        q, sc = _block_quant(part, block, bits, sub)
        q = lax.ppermute(q, axis_name, perm)
        sc = lax.ppermute(sc, axis_name, perm)
        acc = _block_dequant(q, sc)
    own = (r + 1) % W
    final = jnp.take(chunks, own, axis=0) + acc   # my chunk's full sum

    # ring all-gather of the quantized final chunks (own chunk exact)
    key, sub = jax.random.split(key)
    q, sc = _block_quant(final, block, bits, sub)
    out = jnp.zeros((W,) + final.shape, jnp.float32)
    out = _scatter_row(out, own, final)
    for s in range(W - 1):
        q = lax.ppermute(q, axis_name, perm)
        sc = lax.ppermute(sc, axis_name, perm)
        src = (r - s) % W                 # owner of the arriving chunk
        out = _scatter_row(out, src, _block_dequant(q, sc))
    return out.reshape(-1)[:n].reshape(x.shape).astype(orig_dtype)


def bf16_all_reduce(x, axis_name: str):
    """2x-compressed all-reduce: the psum OPERAND is bf16 so bf16 is
    what crosses the wire (casting back before the psum would put fp32
    on the link and save nothing).  Accumulation is bf16 — the standard
    fp16_allreduce trade; use the int8 ring when fp32 accumulation
    matters."""
    return lax.psum(x.astype(jnp.bfloat16), axis_name).astype(x.dtype)


def compressed_psum_tree(tree, axis_name: str, mode: str = "int8",
                         key=None, **kw):
    """Apply the compressed all-reduce across a pytree of gradients.
    mode: 'int8' (EQuARX ring), 'bf16', or 'none' (exact psum)."""
    if mode == "none":
        return jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), tree)
    if mode == "bf16":
        return jax.tree_util.tree_map(
            lambda g: bf16_all_reduce(g, axis_name), tree)
    if mode != "int8":
        raise ValueError(f"unknown compressed allreduce mode {mode!r}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if key is None:
        key = jax.random.PRNGKey(17)
    out = []
    for i, leaf in enumerate(leaves):
        out.append(quantized_all_reduce(
            leaf, axis_name, key=jax.random.fold_in(key, i), **kw))
    return jax.tree_util.tree_unflatten(treedef, out)
